"""Gang supervision chaos tests (ISSUE 3 tentpole acceptance).

The fault injector (``TDL_FAULT_SPEC``) drives deterministic crashes/hangs
through the REAL recovery path: heartbeat files from ``ParallelTrainer``,
liveness polling in ``GangSupervisor``, whole-gang kill, respawn on a fresh
coordinator port, restore from the latest sharded checkpoint. The graduation
of ``test_kill_one_process_restore_from_checkpoint``: the supervisor
reproduces the run unattended.

Fast unit tests for the fault-spec grammar, heartbeat files, bind-failure
classification and launch port-retry live here too.
"""

import json
import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common import faults
from deeplearning4j_tpu.common.faults import FaultInjector, parse_fault_spec
from deeplearning4j_tpu.monitoring.heartbeat import (HeartbeatWriter,
                                                     read_heartbeat)
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.parallel import GangFailedError, GangSupervisor, launcher

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")


# ------------------------------------------------------------------ fault spec


def test_fault_spec_parsing():
    fs = parse_fault_spec("crash@iter=7,rank=1;hang@iter=5,rank=0;slow_ckpt_io=2.0")
    assert [f.kind for f in fs] == ["crash", "hang", "slow_ckpt_io"]
    assert fs[0].iteration == 7 and fs[0].rank == 1
    assert fs[1].iteration == 5 and fs[1].rank == 0
    assert fs[2].value == 2.0
    assert parse_fault_spec("") == []
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_fault_spec("explode@iter=1")
    with pytest.raises(ValueError, match="bad fault param"):
        parse_fault_spec("crash@iter")


def test_fault_incarnation_gating():
    f = parse_fault_spec("crash@iter=3,rank=0")[0]
    assert f.fires_in_incarnation(0) and not f.fires_in_incarnation(1)
    f = parse_fault_spec("crash@iter=3,every=1")[0]
    assert f.fires_in_incarnation(0) and f.fires_in_incarnation(7)
    f = parse_fault_spec("crash@iter=3,restart=2")[0]
    assert f.fires_in_incarnation(2) and not f.fires_in_incarnation(0)


def test_fault_injector_rank_and_iteration_match():
    inj = FaultInjector(parse_fault_spec("crash@iter=7,rank=1"), rank=0,
                        incarnation=0)
    inj.fire("train_step", iteration=7)  # wrong rank: no crash
    inj = FaultInjector(parse_fault_spec("crash@iter=7,rank=1"), rank=1,
                        incarnation=1)
    inj.fire("train_step", iteration=7)  # wrong incarnation: no crash


def test_fault_point_slow_ckpt_io(monkeypatch):
    monkeypatch.setenv(faults.ENV_SPEC, "slow_ckpt_io=0.15")
    t0 = time.perf_counter()
    faults.fault_point("ckpt_write")
    assert time.perf_counter() - t0 >= 0.15
    t0 = time.perf_counter()
    faults.fault_point("train_step", iteration=3)  # site mismatch: no sleep
    assert time.perf_counter() - t0 < 0.1


# ------------------------------------------------------------------ heartbeats


def test_heartbeat_write_read_roundtrip(tmp_path):
    w = HeartbeatWriter(str(tmp_path), rank=3, interval=0.0)
    assert read_heartbeat(str(tmp_path), 3) is None
    assert w.beat(5)
    it, mtime = read_heartbeat(str(tmp_path), 3)
    assert it == 5 and mtime > 0
    assert w.beat(6)
    assert read_heartbeat(str(tmp_path), 3)[0] == 6


def test_heartbeat_throttle(tmp_path):
    w = HeartbeatWriter(str(tmp_path), rank=0, interval=60.0)
    assert w.beat(1)           # first beat always writes
    assert not w.beat(2)       # throttled
    assert w.iteration == 2    # in-memory progress still tracked
    assert read_heartbeat(str(tmp_path), 0)[0] == 1


def test_maybe_beat_env_contract(tmp_path, monkeypatch):
    from deeplearning4j_tpu.monitoring import heartbeat as hb

    monkeypatch.delenv(hb.ENV_DIR, raising=False)
    monkeypatch.setattr(hb, "_writer", None)
    hb.maybe_beat(1)  # no dir: no-op, no writer created
    assert hb._writer is None
    monkeypatch.setenv(hb.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(hb.ENV_INTERVAL, "0")
    monkeypatch.setenv(hb.ENV_RANK, "2")
    hb.maybe_beat(9)
    assert read_heartbeat(str(tmp_path), 2)[0] == 9


# ------------------------------------------- port TOCTOU / bind classification


def test_coordinator_bind_failure_classifier():
    ok = launcher.WorkerResult(0, 0, "", "Address already in use")  # rc 0
    crash = launcher.WorkerResult(0, 1, "", "ValueError: bad batch")
    bind = launcher.WorkerResult(0, 1, "", "RuntimeError: Failed to bind "
                                           "address 127.0.0.1:12345")
    # bind-ish stderr on a NON-coordinator rank is that worker's own failure
    # (e.g. its local HTTP server port) — must NOT classify as the TOCTOU
    sibling = launcher.WorkerResult(1, 1, "", "UNKNOWN: Address already in use")
    assert not launcher.coordinator_bind_failed([ok])
    assert not launcher.coordinator_bind_failed([crash])
    assert launcher.coordinator_bind_failed([bind])
    assert not launcher.coordinator_bind_failed([ok, sibling])
    assert launcher.coordinator_bind_failed([bind, sibling])


def test_launch_retries_on_bind_failure(monkeypatch):
    spawns = []

    def fake_spawn(*a, **k):
        spawns.append(1)
        return ["proc"]

    def fake_wait(procs, timeout=600.0, abort_on_failure=False):
        if len(spawns) == 1:
            return [launcher.WorkerResult(
                0, 1, "", "RuntimeError: Failed to bind address")]
        return [launcher.WorkerResult(0, 0, "done", "")]

    monkeypatch.setattr(launcher, "spawn", fake_spawn)
    monkeypatch.setattr(launcher, "wait", fake_wait)
    results = launcher.launch("m:f", n_processes=1)
    assert len(spawns) == 2  # fresh free_port() inside the second spawn
    assert results[0].returncode == 0


# ------------------------------------------------------------------ chaos runs
# Full-gang chaos runs spawn real 2-process jax gangs several times over
# (~20s each) — slow-marked like the rest of the long multiprocess tier;
# run explicitly with `pytest tests/test_supervisor.py -m slow`.


def _reference_params(steps):
    """Single-process uninterrupted run on the same deterministic batches —
    the ground truth the supervised (crashed + restarted) gang must match."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from tests.mp_workers import _global_batch, _toy_net

    net = _toy_net()
    for step in range(steps):
        x, y = _global_batch(step)
        net.fit(DataSet(x, y))
    flat = np.asarray(net.params().numpy(), np.float64)
    return float(flat.sum()), float(np.linalg.norm(flat))


def _supervisor(tmp_path, fault_spec, steps, every=2, **kw):
    out = str(tmp_path / "out.json")
    env = {"TDL_MP_OUT": out,
           "TDL_MP_CKPT": str(tmp_path / "ckpt"),
           "TDL_MP_STEPS": str(steps),
           "TDL_MP_CKPT_EVERY": str(every),
           "TDL_MATMUL_PRECISION": "float32"}
    if fault_spec:
        env["TDL_FAULT_SPEC"] = fault_spec
    os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
    registry = MetricsRegistry()
    kw.setdefault("hang_timeout", 60.0)
    kw.setdefault("startup_grace", 300.0)
    kw.setdefault("ckpt_dir", env["TDL_MP_CKPT"])  # postmortem lineage state
    sup = GangSupervisor(f"{WORKERS}:supervised_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, backoff_base=0.1,
                         kill_grace=1.0, registry=registry, **kw)
    return sup, out, registry


@pytest.mark.slow
def test_supervisor_recovers_from_injected_crash(tmp_path):
    """Acceptance: TDL_FAULT_SPEC=crash@iter=7,rank=1 → the supervisor
    completes training unattended with ≥1 restart in tdl_gang_restarts_total
    and final params matching the fault-free run."""
    steps = 10
    sup, out, reg = _supervisor(tmp_path, "crash@iter=7,rank=1", steps,
                                max_restarts=3)
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"

    assert sup.restarts >= 1
    assert reg.get("tdl_gang_restarts_total").value >= 1
    assert reg.get("tdl_worker_deaths_total").labels("crash").value >= 1
    assert reg.get("tdl_gang_recovery_seconds").snapshot()["series"][0]["count"] >= 1

    crash_events = [e for e in sup.events if e.reason == "crash"]
    assert crash_events and 1 in crash_events[0].ranks
    assert crash_events[0].iteration == 7  # heartbeat attributed the death

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["incarnation"] >= 1
    assert r0["start"] == 6  # ckpt after step 5 survived; crash was at 7
    ref_sum, ref_norm = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r0["param_norm"], ref_norm, rtol=1e-4)


@pytest.mark.slow
def test_supervisor_detects_hang_well_before_gang_timeout(tmp_path):
    """A wedged rank (injected hang) stalls its heartbeat; the supervisor
    kills and restarts the gang in ~hang_timeout — two orders of magnitude
    under the 600s gang timeout the launcher alone would burn."""
    steps = 8
    hang_timeout = 8.0
    sup, out, reg = _supervisor(tmp_path, "hang@iter=5,rank=0", steps,
                                max_restarts=2, hang_timeout=hang_timeout)
    t0 = time.monotonic()
    results = sup.run(timeout=540.0)
    elapsed = time.monotonic() - t0
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"

    hang_events = [e for e in sup.events if e.reason == "hang"]
    assert hang_events, [e.reason for e in sup.events]
    assert 0 in hang_events[0].ranks
    assert sup.restarts == 1
    assert reg.get("tdl_worker_deaths_total").labels("hang").value >= 1
    # the whole supervised run (spawn + train + detect + respawn + finish)
    # fits in a fraction of the 600s gang timeout
    assert elapsed < 300.0, elapsed

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["start"] == 4  # ckpt after step 3; hang froze iteration 5
    ref_sum, _ = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)


# -------------------------------------------- checkpoint kill-matrix (15)


@pytest.mark.slow
@pytest.mark.parametrize("spec,expected_start", [
    # SIGKILL (os._exit) at each two-phase-commit boundary of the save at
    # iteration 4. Before the pointer swap nothing vouches for gen-4: the
    # respawn quarantines the torn generation and restores the last
    # COMMITTED one (start=2). After COMMIT is durable (stage=pointer),
    # gen-4 IS the checkpoint — iteration order outranks the stale pointer.
    ("torn_ckpt@iter=4,stage=shard,rank=0", 2),
    ("torn_ckpt@iter=4,stage=manifest,rank=0", 2),
    ("torn_ckpt@iter=4,stage=commit,rank=0", 2),
    ("torn_ckpt@iter=4,stage=pointer,rank=0", 4),
    # disk-full at the write site: the save RAISES (worker crash), the
    # generation stays uncommitted, recovery replays from the last commit
    ("enospc@iter=4,rank=0", 2),
])
def test_kill_matrix_every_commit_boundary_recovers_unattended(
        tmp_path, spec, expected_start):
    """ISSUE 15 acceptance: a kill at ANY instant of the two-phase commit
    leaves either the old or the new generation fully restorable — the
    supervisor respawns, the workers quarantine/fall back on their own, and
    the final params match the unfaulted reference."""
    steps = 8
    sup, out, reg = _supervisor(tmp_path, spec, steps, max_restarts=3)
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1
    # torn_ckpt is a hard os._exit (crash); an injected enospc raises out of
    # the worker, which may either exit nonzero (crash) or wedge on gloo
    # teardown until the heartbeat stall condemns it (hang) — both are the
    # supervisor doing its job
    deaths = reg.get("tdl_worker_deaths_total")
    assert deaths.labels("crash").value + deaths.labels("hang").value >= 1

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["incarnation"] >= 1
    assert r0["start"] == expected_start, (spec, r0["start"])
    ref_sum, ref_norm = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r0["param_norm"], ref_norm, rtol=1e-4)

    # the postmortem carries the checkpoint lineage inventory (ckpt_dir)
    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert "checkpoint" in pm
    if expected_start == 2:
        # the torn generation healed: quarantined + evidenced on disk. The
        # postmortem was RE-written after the successful recovery.
        assert pm["classification"] == "recovered"
        assert any(e.get("kind") == "ckpt_quarantine" for e in pm["events"])
        assert any("gen-00000004" in q
                   for q in pm["checkpoint"]["quarantined"])
    else:
        # stage=pointer: gen-4 committed, pointer one behind at kill time
        committed = [g["generation"] for g in pm["checkpoint"]["committed"]]
        assert "gen-00000004" in committed


@pytest.mark.slow
def test_corrupt_committed_shard_quarantine_and_fallback_recovery(tmp_path):
    """ISSUE 15 acceptance: a bit-flip in a COMMITTED shard (latent disk
    corruption, injected right after the commit at iteration 4) plus a
    later crash — the respawned gang's restore catches the corruption via
    the manifest CRCs, quarantines gen-4, FALLS BACK to gen-2, and finishes
    with params matching the unfaulted reference. Quarantine + fallback are
    evidenced in postmortem.json and in the spooled worker metrics."""
    from deeplearning4j_tpu.monitoring import aggregate

    steps = 8
    sup, out, reg = _supervisor(
        tmp_path, "corrupt_ckpt@iter=4,rank=0;crash@iter=5,rank=1", steps,
        max_restarts=3)
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["start"] == 2  # fell back PAST the corrupt gen-4 commit
    ref_sum, ref_norm = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r0["param_norm"], ref_norm, rtol=1e-4)

    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert pm["classification"] == "recovered"
    quar = [e for e in pm["events"] if e.get("kind") == "ckpt_quarantine"]
    fb = [e for e in pm["events"] if e.get("kind") == "ckpt_fallback"]
    assert quar and quar[0]["generation"] == "gen-00000004"
    assert fb and fb[0]["from_generation"] == "gen-00000004"
    assert fb[0]["to_generation"] == "gen-00000002"
    assert any("gen-00000004" in q for q in pm["checkpoint"]["quarantined"])
    # metrics: the workers' spooled registries carry the lineage counters
    spools = aggregate.read_spools(sup.spool_dir)
    quarantined = fallbacks = 0.0
    for spool in spools:
        for fam, snap in spool.get("snapshot", {}).items():
            if fam == "tdl_ckpt_quarantined_total":
                quarantined += sum(s["value"] for s in snap["series"])
            if fam == "tdl_ckpt_fallback_restores_total":
                fallbacks += sum(s["value"] for s in snap["series"])
    assert quarantined >= 1 and fallbacks >= 1


# ------------------------------------------------------- elastic resize (14)


def _resize_supervisor(tmp_path, n=2, **kw):
    from deeplearning4j_tpu.parallel.supervisor import GangEvent

    kw.setdefault("elastic", True)
    kw.setdefault("max_restarts", 2)
    sup = GangSupervisor(f"{WORKERS}:elastic_train", n_processes=n,
                         n_local_devices=2, workdir=str(tmp_path / "gang"),
                         registry=MetricsRegistry(), **kw)
    return sup, GangEvent


def test_try_resize_degrades_to_survivors(tmp_path):
    """The elastic decision logic, pinned without processes: the consistent
    culprit set shrinks the gang, records the metric/flight entry and grants
    a fresh budget; inconsistent culprits or a floor breach refuse."""
    sup, GangEvent = _resize_supervisor(tmp_path, n=4)
    sup._restarts_this_size = 2
    sup.events = [GangEvent(1.0, "crash", 0, (1, 3), 5),
                  GangEvent(2.0, "crash", 1, (3,), None),
                  GangEvent(3.0, "crash", 2, (3,), None)]
    assert sup._try_resize(sup.events[-1])
    assert sup.n_processes == 3          # rank 3 was in EVERY failure
    assert sup._restarts_this_size == 0  # fresh budget at the new size
    assert sup.resizes[0]["suspect_ranks"] == [3]
    assert sup.resizes[0]["from_processes"] == 4
    assert sup.resizes[0]["to_processes"] == 3
    # the survivor layout is the largest valid one for the remaining devices
    assert sup.resizes[0]["survivor_layout"]["axes"]["fsdp"] == 6
    snap = sup.registry.get("tdl_gang_resizes_total").snapshot()
    assert [(s["labels"], s["value"]) for s in snap["series"]] == [
        ({"direction": "down"}, 1.0)]


def test_try_resize_ignores_bind_events_and_pre_resize_history(tmp_path):
    """Only crash/hang failures AT the current size vote: a bind race (own
    budget, implicates rank 0 by construction) must not poison the suspect
    intersection, and events from before a previous resize carry renumbered
    rank ids."""
    sup, GangEvent = _resize_supervisor(tmp_path, n=2)
    sup._restarts_this_size = 2
    sup.events = [GangEvent(0.5, "bind", 0, (0,), None),
                  GangEvent(1.0, "crash", 1, (1,), 3),
                  GangEvent(1.5, "bind", 1, (0,), None),
                  GangEvent(2.0, "crash", 2, (1,), None),
                  GangEvent(3.0, "crash", 3, (1,), None)]
    assert sup._try_resize(sup.events[-1])
    assert sup.n_processes == 1
    assert sup.resizes[0]["suspect_ranks"] == [1]
    # events from the bigger gang are fenced off for the NEXT analysis
    assert sup._events_mark == len(sup.events)


def test_try_resize_refuses_without_consistent_culprit(tmp_path):
    sup, GangEvent = _resize_supervisor(tmp_path, n=2)
    # wandering ranks: no intersection — a software fault, not a dead host
    sup.events = [GangEvent(1.0, "crash", 0, (0,), 3),
                  GangEvent(2.0, "crash", 1, (1,), 3)]
    assert not sup._try_resize(sup.events[-1])
    assert sup.n_processes == 2 and sup.resizes == []


def test_try_resize_respects_min_processes_and_elastic_flag(tmp_path):
    sup, GangEvent = _resize_supervisor(tmp_path, n=2, min_processes=2)
    ev = [GangEvent(1.0, "crash", 0, (1,), None)] * 3
    sup.events = list(ev)
    assert not sup._try_resize(ev[-1])   # floor: 1 survivor < min_processes
    sup2, _ = _resize_supervisor(tmp_path, n=2, elastic=False)
    sup2.events = list(ev)
    assert not sup2._try_resize(ev[-1])  # elastic is opt-in


@pytest.mark.slow
def test_elastic_gang_resizes_to_survivors_and_finishes(tmp_path):
    """ISSUE 14 acceptance: a rank whose 'host' never comes back (exits at
    boot in every respawn) exhausts the restart budget; the supervisor
    degrades the gang to the single survivor instead of classifying fatal,
    the survivor restores the bigger gang's checkpoint CROSS-TOPOLOGY
    (fsdp=4 shards onto the fsdp=2 survivor mesh) and finishes training
    unattended; the postmortem records the resize."""
    steps = 8
    ckdir = tmp_path / "ckpt"
    ckdir.mkdir()
    env = {"TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MP_CKPT": str(ckdir),
           "TDL_MP_STEPS": str(steps), "TDL_MP_CKPT_EVERY": "2",
           "TDL_MP_DEAD_RANK": "1", "TDL_MP_SURVIVORS": "1",
           "TDL_MATMUL_PRECISION": "float32",
           # incarnation 0 trains past a checkpoint, then loses rank 1;
           # every later incarnation loses it at BOOT via TDL_MP_DEAD_RANK
           "TDL_FAULT_SPEC": "crash@iter=3,rank=1"}
    reg = MetricsRegistry()
    sup = GangSupervisor(f"{WORKERS}:elastic_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, backoff_base=0.1,
                         kill_grace=1.0, max_restarts=2, elastic=True,
                         min_processes=1, hang_timeout=60.0,
                         startup_grace=300.0, registry=reg)
    results = sup.run(timeout=540.0)
    assert len(results) == 1  # the final gang IS the survivor gang
    assert results[0].returncode == 0, results[0].stderr[-3000:]

    assert sup.n_processes == 1
    assert len(sup.resizes) == 1
    rz = sup.resizes[0]
    assert rz["from_processes"] == 2 and rz["to_processes"] == 1
    assert rz["suspect_ranks"] == [1]
    snap = reg.get("tdl_gang_resizes_total").snapshot()
    assert snap["series"][0]["labels"] == {"direction": "down"}
    assert snap["series"][0]["value"] == 1.0

    # the postmortem (re-written at the resize decision) carries the story
    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert pm["classification"] == "elastic_resize"
    assert pm["resizes"][0]["to_processes"] == 1
    assert pm["resizes"][0]["suspect_ranks"] == [1]
    assert pm["gang_size"] == 1

    with open(str(tmp_path / "out.json") + ".rank0") as f:
        r0 = json.load(f)
    assert r0["world"] == 1
    assert r0["start"] == 2      # restored the fsdp=4 ckpt from iteration 2
    assert r0["iteration"] == steps
    assert r0["mesh"]["fsdp"] == 2  # survivor mesh: 1 proc x 2 devices

    # parity: steps 0-2 ran fsdp=4, the rest fsdp=2 — both match the
    # replicated math, so the final params match a straight single run
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import (DenseLayer, InputType,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    ref = MultiLayerNetwork(conf).init()
    for step in range(steps):
        rs = np.random.RandomState(2000 + step)
        x = rs.rand(8, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 8)]
        ref.fit(DataSet(x, y))
    import jax.numpy as jnp

    ref_sum = float(sum(jnp.sum(w) for w in jax.tree.leaves(ref.params_)))
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_repeated_crash_same_iteration_is_fatal(tmp_path):
    """A deterministic fault (crash at the same iteration every incarnation)
    must be classified fatal and surfaced — not retried until the restart
    budget burns down."""
    sup, out, reg = _supervisor(tmp_path, "crash@iter=3,rank=1,every=1",
                                steps=6, max_restarts=5,
                                same_iteration_fatal=2)
    with pytest.raises(GangFailedError) as ei:
        sup.run(timeout=540.0)
    assert ei.value.classification == "repeated_crash_same_iteration"
    assert sup.restarts < sup.max_restarts  # budget NOT exhausted: classified
    assert reg.get("tdl_worker_deaths_total").labels("crash").value == 2
    assert len([e for e in ei.value.events if e.reason == "crash"]) == 2
