"""Gang supervision chaos tests (ISSUE 3 tentpole acceptance).

The fault injector (``TDL_FAULT_SPEC``) drives deterministic crashes/hangs
through the REAL recovery path: heartbeat files from ``ParallelTrainer``,
liveness polling in ``GangSupervisor``, whole-gang kill, respawn on a fresh
coordinator port, restore from the latest sharded checkpoint. The graduation
of ``test_kill_one_process_restore_from_checkpoint``: the supervisor
reproduces the run unattended.

Fast unit tests for the fault-spec grammar, heartbeat files, bind-failure
classification and launch port-retry live here too.
"""

import json
import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common import faults
from deeplearning4j_tpu.common.faults import FaultInjector, parse_fault_spec
from deeplearning4j_tpu.monitoring.heartbeat import (HeartbeatWriter,
                                                     read_heartbeat)
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.parallel import GangFailedError, GangSupervisor, launcher

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")


# ------------------------------------------------------------------ fault spec


def test_fault_spec_parsing():
    fs = parse_fault_spec("crash@iter=7,rank=1;hang@iter=5,rank=0;slow_ckpt_io=2.0")
    assert [f.kind for f in fs] == ["crash", "hang", "slow_ckpt_io"]
    assert fs[0].iteration == 7 and fs[0].rank == 1
    assert fs[1].iteration == 5 and fs[1].rank == 0
    assert fs[2].value == 2.0
    assert parse_fault_spec("") == []
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_fault_spec("explode@iter=1")
    with pytest.raises(ValueError, match="bad fault param"):
        parse_fault_spec("crash@iter")


def test_fault_incarnation_gating():
    f = parse_fault_spec("crash@iter=3,rank=0")[0]
    assert f.fires_in_incarnation(0) and not f.fires_in_incarnation(1)
    f = parse_fault_spec("crash@iter=3,every=1")[0]
    assert f.fires_in_incarnation(0) and f.fires_in_incarnation(7)
    f = parse_fault_spec("crash@iter=3,restart=2")[0]
    assert f.fires_in_incarnation(2) and not f.fires_in_incarnation(0)


def test_fault_injector_rank_and_iteration_match():
    inj = FaultInjector(parse_fault_spec("crash@iter=7,rank=1"), rank=0,
                        incarnation=0)
    inj.fire("train_step", iteration=7)  # wrong rank: no crash
    inj = FaultInjector(parse_fault_spec("crash@iter=7,rank=1"), rank=1,
                        incarnation=1)
    inj.fire("train_step", iteration=7)  # wrong incarnation: no crash


def test_fault_point_slow_ckpt_io(monkeypatch):
    monkeypatch.setenv(faults.ENV_SPEC, "slow_ckpt_io=0.15")
    t0 = time.perf_counter()
    faults.fault_point("ckpt_write")
    assert time.perf_counter() - t0 >= 0.15
    t0 = time.perf_counter()
    faults.fault_point("train_step", iteration=3)  # site mismatch: no sleep
    assert time.perf_counter() - t0 < 0.1


# ------------------------------------------------------------------ heartbeats


def test_heartbeat_write_read_roundtrip(tmp_path):
    w = HeartbeatWriter(str(tmp_path), rank=3, interval=0.0)
    assert read_heartbeat(str(tmp_path), 3) is None
    assert w.beat(5)
    it, mtime = read_heartbeat(str(tmp_path), 3)
    assert it == 5 and mtime > 0
    assert w.beat(6)
    assert read_heartbeat(str(tmp_path), 3)[0] == 6


def test_heartbeat_throttle(tmp_path):
    w = HeartbeatWriter(str(tmp_path), rank=0, interval=60.0)
    assert w.beat(1)           # first beat always writes
    assert not w.beat(2)       # throttled
    assert w.iteration == 2    # in-memory progress still tracked
    assert read_heartbeat(str(tmp_path), 0)[0] == 1


def test_maybe_beat_env_contract(tmp_path, monkeypatch):
    from deeplearning4j_tpu.monitoring import heartbeat as hb

    monkeypatch.delenv(hb.ENV_DIR, raising=False)
    monkeypatch.setattr(hb, "_writer", None)
    hb.maybe_beat(1)  # no dir: no-op, no writer created
    assert hb._writer is None
    monkeypatch.setenv(hb.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(hb.ENV_INTERVAL, "0")
    monkeypatch.setenv(hb.ENV_RANK, "2")
    hb.maybe_beat(9)
    assert read_heartbeat(str(tmp_path), 2)[0] == 9


# ------------------------------------------- port TOCTOU / bind classification


def test_coordinator_bind_failure_classifier():
    ok = launcher.WorkerResult(0, 0, "", "Address already in use")  # rc 0
    crash = launcher.WorkerResult(0, 1, "", "ValueError: bad batch")
    bind = launcher.WorkerResult(0, 1, "", "RuntimeError: Failed to bind "
                                           "address 127.0.0.1:12345")
    # bind-ish stderr on a NON-coordinator rank is that worker's own failure
    # (e.g. its local HTTP server port) — must NOT classify as the TOCTOU
    sibling = launcher.WorkerResult(1, 1, "", "UNKNOWN: Address already in use")
    assert not launcher.coordinator_bind_failed([ok])
    assert not launcher.coordinator_bind_failed([crash])
    assert launcher.coordinator_bind_failed([bind])
    assert not launcher.coordinator_bind_failed([ok, sibling])
    assert launcher.coordinator_bind_failed([bind, sibling])


def test_launch_retries_on_bind_failure(monkeypatch):
    spawns = []

    def fake_spawn(*a, **k):
        spawns.append(1)
        return ["proc"]

    def fake_wait(procs, timeout=600.0, abort_on_failure=False):
        if len(spawns) == 1:
            return [launcher.WorkerResult(
                0, 1, "", "RuntimeError: Failed to bind address")]
        return [launcher.WorkerResult(0, 0, "done", "")]

    monkeypatch.setattr(launcher, "spawn", fake_spawn)
    monkeypatch.setattr(launcher, "wait", fake_wait)
    results = launcher.launch("m:f", n_processes=1)
    assert len(spawns) == 2  # fresh free_port() inside the second spawn
    assert results[0].returncode == 0


# ------------------------------------------------------------------ chaos runs
# Full-gang chaos runs spawn real 2-process jax gangs several times over
# (~20s each) — slow-marked like the rest of the long multiprocess tier;
# run explicitly with `pytest tests/test_supervisor.py -m slow`.


def _reference_params(steps):
    """Single-process uninterrupted run on the same deterministic batches —
    the ground truth the supervised (crashed + restarted) gang must match."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from tests.mp_workers import _global_batch, _toy_net

    net = _toy_net()
    for step in range(steps):
        x, y = _global_batch(step)
        net.fit(DataSet(x, y))
    flat = np.asarray(net.params().numpy(), np.float64)
    return float(flat.sum()), float(np.linalg.norm(flat))


def _supervisor(tmp_path, fault_spec, steps, every=2, **kw):
    out = str(tmp_path / "out.json")
    env = {"TDL_MP_OUT": out,
           "TDL_MP_CKPT": str(tmp_path / "ckpt"),
           "TDL_MP_STEPS": str(steps),
           "TDL_MP_CKPT_EVERY": str(every),
           "TDL_MATMUL_PRECISION": "float32"}
    if fault_spec:
        env["TDL_FAULT_SPEC"] = fault_spec
    os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
    registry = MetricsRegistry()
    kw.setdefault("hang_timeout", 60.0)
    kw.setdefault("startup_grace", 300.0)
    sup = GangSupervisor(f"{WORKERS}:supervised_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, backoff_base=0.1,
                         kill_grace=1.0, registry=registry, **kw)
    return sup, out, registry


@pytest.mark.slow
def test_supervisor_recovers_from_injected_crash(tmp_path):
    """Acceptance: TDL_FAULT_SPEC=crash@iter=7,rank=1 → the supervisor
    completes training unattended with ≥1 restart in tdl_gang_restarts_total
    and final params matching the fault-free run."""
    steps = 10
    sup, out, reg = _supervisor(tmp_path, "crash@iter=7,rank=1", steps,
                                max_restarts=3)
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"

    assert sup.restarts >= 1
    assert reg.get("tdl_gang_restarts_total").value >= 1
    assert reg.get("tdl_worker_deaths_total").labels("crash").value >= 1
    assert reg.get("tdl_gang_recovery_seconds").snapshot()["series"][0]["count"] >= 1

    crash_events = [e for e in sup.events if e.reason == "crash"]
    assert crash_events and 1 in crash_events[0].ranks
    assert crash_events[0].iteration == 7  # heartbeat attributed the death

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["incarnation"] >= 1
    assert r0["start"] == 6  # ckpt after step 5 survived; crash was at 7
    ref_sum, ref_norm = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r0["param_norm"], ref_norm, rtol=1e-4)


@pytest.mark.slow
def test_supervisor_detects_hang_well_before_gang_timeout(tmp_path):
    """A wedged rank (injected hang) stalls its heartbeat; the supervisor
    kills and restarts the gang in ~hang_timeout — two orders of magnitude
    under the 600s gang timeout the launcher alone would burn."""
    steps = 8
    hang_timeout = 8.0
    sup, out, reg = _supervisor(tmp_path, "hang@iter=5,rank=0", steps,
                                max_restarts=2, hang_timeout=hang_timeout)
    t0 = time.monotonic()
    results = sup.run(timeout=540.0)
    elapsed = time.monotonic() - t0
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"

    hang_events = [e for e in sup.events if e.reason == "hang"]
    assert hang_events, [e.reason for e in sup.events]
    assert 0 in hang_events[0].ranks
    assert sup.restarts == 1
    assert reg.get("tdl_worker_deaths_total").labels("hang").value >= 1
    # the whole supervised run (spawn + train + detect + respawn + finish)
    # fits in a fraction of the 600s gang timeout
    assert elapsed < 300.0, elapsed

    with open(out + ".rank0") as f:
        r0 = json.load(f)
    assert r0["start"] == 4  # ckpt after step 3; hang froze iteration 5
    ref_sum, _ = _reference_params(steps)
    np.testing.assert_allclose(r0["param_sum"], ref_sum, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_repeated_crash_same_iteration_is_fatal(tmp_path):
    """A deterministic fault (crash at the same iteration every incarnation)
    must be classified fatal and surfaced — not retried until the restart
    budget burns down."""
    sup, out, reg = _supervisor(tmp_path, "crash@iter=3,rank=1,every=1",
                                steps=6, max_restarts=5,
                                same_iteration_fatal=2)
    with pytest.raises(GangFailedError) as ei:
        sup.run(timeout=540.0)
    assert ei.value.classification == "repeated_crash_same_iteration"
    assert sup.restarts < sup.max_restarts  # budget NOT exhausted: classified
    assert reg.get("tdl_worker_deaths_total").labels("crash").value == 2
    assert len([e for e in ei.value.events if e.reason == "crash"]) == 2
