"""Cluster-wide observability plane (ISSUE 7).

Layer 1 (aggregation): per-process registry spools merged into ONE
proc/rank-labeled /metrics with derived straggler gauges. Layer 2 (flight
recorder): bounded event rings merged into a monotonic-ordered
postmortem.json on gang failure. Layer 3 (attribution): per-step
input/h2d/compute/collective breakdown through monitoring.trace.

Satellites covered here: the strict Prometheus round-trip (escaping), the
wall-clock AST lint, registry-across-spawn isolation, the last-failure
info gauge, and bench.py's --check-telemetry contract.

The slow tier spawns real 2-process gangs under GangSupervisor — the
acceptance runs for the aggregated scrape + skew gauge and for the
crash postmortem.
"""

import ast
import json
import multiprocessing
import os
import pathlib
import re
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import aggregate, flight
from deeplearning4j_tpu.monitoring.aggregate import (MetricsSpooler,
                                                     derive_straggler,
                                                     merged_prometheus)
from deeplearning4j_tpu.monitoring.flight import FlightRecorder, merge_events
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.monitoring.trace import StepPhaseRecorder

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")
ROOT = pathlib.Path(__file__).resolve().parent.parent


# ------------------------------------------------------- strict text parser


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_ESCAPES = {"\\": "\\", "n": "\n", '"': '"'}


def _parse_sample(line):
    """One sample line, strictly: name{label="value",...} value. Raises on
    anything a real Prometheus scraper would reject."""
    brace = line.find("{")
    if brace == -1:
        name, _, value = line.partition(" ")
        assert _NAME_RE.match(name), f"bad metric name {name!r}"
        return name, (), float(value)
    name = line[:brace]
    assert _NAME_RE.match(name), f"bad metric name {name!r}"
    labels = []
    j = brace + 1
    while line[j] != "}":
        eq = line.index("=", j)
        key = line[j:eq]
        assert _NAME_RE.match(key), f"bad label name {key!r}"
        assert line[eq + 1] == '"', f"unquoted label value in {line!r}"
        j = eq + 2
        buf = []
        while True:
            c = line[j]
            if c == "\\":
                esc = line[j + 1]
                assert esc in _ESCAPES, f"bad escape \\{esc} in {line!r}"
                buf.append(_ESCAPES[esc])
                j += 2
            elif c == '"':
                j += 1
                break
            else:
                buf.append(c)
                j += 1
        labels.append((key, "".join(buf)))
        if line[j] == ",":
            j += 1
    rest = line[j + 1:]
    assert rest.startswith(" "), f"missing space before value in {line!r}"
    return name, tuple(labels), float(rest.strip())


def _parse_prometheus(text):
    """{sample_name: {labels_tuple: value}} with full-format validation."""
    assert text == "" or text.endswith("\n"), "exposition must end in newline"
    out = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            body = line.split(" ", 3)
            assert _NAME_RE.match(body[2]), f"bad name in comment {line!r}"
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        name, labels, value = _parse_sample(line)
        out.setdefault(name, {})[labels] = value
    return out


# ------------------------------------------------- registry escaping (sat 2)


def test_prometheus_escaping_round_trip():
    reg = MetricsRegistry()
    nasty = 'back\\slash"quote"\nnewline'
    reg.counter("tdl_esc_total", "counts\nwith a newline and \\slash in help",
                labels=("path",)).labels(nasty).inc(3)
    reg.gauge("tdl_esc_gauge", labels=("p",)).labels("plain").set(1.5)
    reg.histogram("tdl_esc_hist", labels=("p",),
                  buckets=(0.1, 1.0)).labels(nasty).observe(0.5)
    text = reg.to_prometheus()
    parsed = _parse_prometheus(text)  # raises on any malformed line
    assert parsed["tdl_esc_total"][(("path", nasty),)] == 3
    assert parsed["tdl_esc_gauge"][(("p", "plain"),)] == 1.5
    # histogram children carry the escaped labels too, plus le
    assert parsed["tdl_esc_hist_bucket"][(("p", nasty), ("le", "1"))] == 1
    assert parsed["tdl_esc_hist_count"][(("p", nasty),)] == 1


def test_registry_label_name_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="invalid label name"):
        reg.counter("tdl_bad_total", labels=('quo"te',))
    with pytest.raises(ValueError, match="invalid label name"):
        reg.gauge("tdl_bad_gauge", labels=("0startsdigit",))


def test_registry_clear_children():
    reg = MetricsRegistry()
    g = reg.gauge("tdl_info", labels=("reason",))
    g.labels("crash").set(1)
    g.labels("hang").set(2)
    assert len(g.snapshot()["series"]) == 2
    g.clear_children()
    g.labels("bind").set(3)
    series = g.snapshot()["series"]
    assert len(series) == 1 and series[0]["labels"] == {"reason": "bind"}


# -------------------------------------------------------- flight recorder


def test_flight_ring_is_bounded_and_ordered():
    rec = FlightRecorder(proc="t", capacity=4)
    for i in range(10):
        rec.record("step_begin", iteration=i)
    evs = rec.events()
    assert len(evs) == 4
    assert [e["iteration"] for e in evs] == [6, 7, 8, 9]
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts)
    assert all(e["proc"] == "t" and e["kind"] == "step_begin" for e in evs)


def test_flight_spool_and_merge(tmp_path):
    a = FlightRecorder(proc="rank0", directory=str(tmp_path), interval=0.0)
    b = FlightRecorder(proc="rank1", directory=str(tmp_path), interval=0.0)
    a.record("step_begin", iteration=0)
    b.record("step_begin", iteration=0)
    a.record("step_end", iteration=0)
    spools = flight.read_spools(str(tmp_path))
    assert {s["proc"] for s in spools} == {"rank0", "rank1"}
    sup = FlightRecorder(proc="supervisor")
    sup.record("gang_failure", reason="crash")
    merged = merge_events(spools, sup.events())
    assert len(merged) == 4
    ts = [e["t"] for e in merged]
    assert ts == sorted(ts)
    assert merged[-1]["kind"] == "gang_failure"


def test_flight_env_contract(tmp_path, monkeypatch):
    monkeypatch.delenv(flight.ENV_DIR, raising=False)
    flight.set_flight_recorder(None)
    assert not flight.active()
    assert flight.record("noop") is None  # no dir: nothing recorded
    monkeypatch.setenv(flight.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(flight.ENV_INTERVAL, "0")
    monkeypatch.setenv(flight.ENV_RANK, "3")
    assert flight.active()
    flight.record("heartbeat", iteration=5)
    spools = flight.read_spools(str(tmp_path))
    assert len(spools) == 1 and spools[0]["proc"] == "rank3"
    assert spools[0]["events"][0]["kind"] == "heartbeat"


def test_fault_injector_records_flight_event(tmp_path, monkeypatch):
    """slow_ckpt_io honors rank= (the straggler fault) and crash/hang leave
    a fault_injected breadcrumb; the crash itself is not executed here —
    the slow-path rank gate is what's under test."""
    from deeplearning4j_tpu.common.faults import FaultInjector, parse_fault_spec

    inj = FaultInjector(parse_fault_spec("slow_ckpt_io@value=0.4,rank=1"),
                        rank=0, incarnation=0)
    t0 = time.perf_counter()
    inj.fire("ckpt_write")  # wrong rank: no sleep (generous load margin)
    assert time.perf_counter() - t0 < 0.3
    inj = FaultInjector(parse_fault_spec("slow_ckpt_io@value=0.4,rank=1"),
                        rank=1, incarnation=0)
    t0 = time.perf_counter()
    inj.fire("ckpt_write")
    assert time.perf_counter() - t0 >= 0.4
    # legacy value-form still fires on every rank
    inj = FaultInjector(parse_fault_spec("slow_ckpt_io=0.05"), rank=7,
                        incarnation=2)
    t0 = time.perf_counter()
    inj.fire("ckpt_write")
    assert time.perf_counter() - t0 >= 0.05


# ---------------------------------------------------- aggregation (layer 1)


def _rank_registry(step_seconds, steps=5):
    reg = MetricsRegistry()
    h = reg.histogram("tdl_step_wall_seconds", "wall", labels=("trainer",))
    for _ in range(steps):
        h.labels("ParallelTrainer").observe(step_seconds)
    reg.counter("tdl_iterations_total", labels=("model",)).labels("M").inc(steps)
    return reg


def test_spooler_writes_and_merges_with_rank_labels(tmp_path):
    MetricsSpooler(str(tmp_path), proc="rank0", registry=_rank_registry(0.01),
                   interval=0.0, rank=0).spool(force=True)
    MetricsSpooler(str(tmp_path), proc="rank1", registry=_rank_registry(0.04),
                   interval=0.0, rank=1).spool(force=True)
    local = MetricsRegistry()
    local.counter("tdl_gang_restarts_total", "restarts").inc()
    text = merged_prometheus(str(tmp_path), local_registry=local,
                             local_proc="supervisor")
    parsed = _parse_prometheus(text)  # strict: the merge must render validly
    counts = parsed["tdl_step_wall_seconds_count"]
    ranks = {dict(k).get("rank") for k in counts}
    assert ranks == {"0", "1"}  # same family, distinct rank labels
    procs = {dict(k).get("proc") for k in counts}
    assert procs == {"rank0", "rank1"}
    assert parsed["tdl_gang_restarts_total"][(("proc", "supervisor"),)] == 1
    # derived straggler gauges ride the merge
    assert parsed["tdl_step_time_skew_ratio"][()] == pytest.approx(4.0)
    assert parsed["tdl_step_time_slowest_rank"][()] == 1
    assert parsed["tdl_step_time_mean_seconds"][(("rank", "1"),)] == pytest.approx(0.04)


def test_read_spools_keeps_newest_per_proc(tmp_path):
    old = {"proc": "rank0", "rank": 0, "pid": 1, "wall": 100.0, "snapshot": {}}
    new = {"proc": "rank0", "rank": 0, "pid": 2, "wall": 200.0,
           "snapshot": {"x": {"type": "counter", "series": []}}}
    for pid, payload in ((1, old), (2, new)):
        with open(tmp_path / f"{aggregate.SPOOL_PREFIX}rank0.{pid}.json", "w") as f:
            json.dump(payload, f)
    (tmp_path / f"{aggregate.SPOOL_PREFIX}torn.3.json").write_text("{nope")
    spools = aggregate.read_spools(str(tmp_path))
    assert len(spools) == 1 and spools[0]["pid"] == 2  # newest wins, torn skipped


def test_derive_straggler_requires_two_ranks():
    spool = lambda rank, mean: {  # noqa: E731
        "rank": rank,
        "snapshot": {"tdl_step_wall_seconds": {
            "type": "histogram",
            "series": [{"count": 4, "sum": 4 * mean}]}}}
    assert derive_straggler([spool(0, 0.01)]) is None
    d = derive_straggler([spool(0, 0.01), spool(1, 0.05), spool(2, 0.02)])
    assert d["slowest_rank"] == 1
    assert d["skew_ratio"] == pytest.approx(5.0)
    assert d["mean_step_seconds"] == {0: pytest.approx(0.01),
                                      1: pytest.approx(0.05),
                                      2: pytest.approx(0.02)}


def test_maybe_spool_env_contract(tmp_path, monkeypatch):
    monkeypatch.delenv(aggregate.ENV_DIR, raising=False)
    aggregate.maybe_spool()  # no env: no-op
    assert not list(tmp_path.iterdir())
    monkeypatch.setenv(aggregate.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(aggregate.ENV_INTERVAL, "0")
    aggregate.maybe_spool(force=True)
    spools = aggregate.read_spools(str(tmp_path))
    assert len(spools) == 1 and spools[0]["pid"] == os.getpid()


def test_ui_server_serves_merged_metrics(tmp_path):
    from deeplearning4j_tpu.ui.server import UIServer

    MetricsSpooler(str(tmp_path), proc="rank0", registry=_rank_registry(0.01),
                   interval=0.0, rank=0).spool(force=True)
    MetricsSpooler(str(tmp_path), proc="rank1", registry=_rank_registry(0.03),
                   interval=0.0, rank=1).spool(force=True)
    ui = UIServer(port=0)
    try:
        ui.attach_spool_dir(str(tmp_path), local_proc="supervisor")
        base = f"http://127.0.0.1:{ui.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            parsed = _parse_prometheus(r.read().decode())
        ranks = {dict(k).get("rank")
                 for k in parsed["tdl_step_wall_seconds_count"]}
        # superset, not equality: the scraping process's OWN registry rides
        # the merge as proc="supervisor" with no rank label, and any earlier
        # test that ran a trainer leaves that series behind (order-dependent
        # flake otherwise — the spooled ranks are what's under test)
        assert {"0", "1"} <= ranks
        assert parsed["tdl_step_time_skew_ratio"][()] == pytest.approx(3.0)
        with urllib.request.urlopen(f"{base}/metrics.json", timeout=10) as r:
            snap = json.loads(r.read().decode())
        assert set(snap["procs"]) == {"rank0", "rank1"}
        assert snap["derived"]["slowest_rank"] == 1
        assert "local" in snap
    finally:
        ui.stop()


# ---------------------------------------------- step-time attribution (3)


def test_step_phase_recorder_exclusive_nesting():
    reg = MetricsRegistry()
    rec = StepPhaseRecorder(registry=reg)
    t0 = time.perf_counter()
    with rec.phase("compute"):
        time.sleep(0.03)
        with rec.phase("h2d"):
            time.sleep(0.03)
    outer = time.perf_counter() - t0
    rec.step_done()
    snap = reg.snapshot()["tdl_step_phase_seconds"]
    series = {s["labels"]["phase"]: s for s in snap["series"]}
    assert series["h2d"]["sum"] >= 0.03
    assert series["compute"]["sum"] >= 0.02
    # exclusive time: the nested h2d slice (≥0.03s by construction) is NOT
    # double-counted in compute — load-robust: compute ≤ outer − child sleep
    assert series["compute"]["sum"] <= outer - 0.029
    summary = rec.summary()
    assert summary["steps"] == 1
    total_pct = sum(p["pct"] for p in summary["phases"].values())
    assert total_pct == pytest.approx(100.0, abs=5.0)
    assert set(summary["phases"]) >= {"input", "h2d", "compute", "collective"}


def test_step_phase_summary_covers_wall():
    rec = StepPhaseRecorder(registry=MetricsRegistry())
    for _ in range(3):
        with rec.phase("input"):
            time.sleep(0.01)
        with rec.phase("compute"):
            time.sleep(0.02)
        rec.step_done()
    s = rec.summary()
    assert s["steps"] == 3
    pct = {k: v["pct"] for k, v in s["phases"].items()}
    assert pct["compute"] > pct["input"] > 0
    assert sum(pct.values()) + s["other_pct"] == pytest.approx(100.0, abs=1.0)
    # the loop is fully instrumented; generous bound for loaded CI hosts
    # (uninstrumented scheduling gaps between phases inflate "other")
    assert s["other_pct"] < 60.0


def test_step_phase_recorder_survives_raising_phase_body():
    """ISSUE 10 satellite: a phase body that raises must not corrupt the
    frame stack or the exclusive-time accounting of the surrounding step."""
    reg = MetricsRegistry()
    rec = StepPhaseRecorder(registry=reg)
    with pytest.raises(RuntimeError):
        with rec.phase("compute"):
            time.sleep(0.01)
            with rec.phase("h2d"):
                raise RuntimeError("h2d blew up")
    assert rec._frames == []  # both frames unwound despite the raise
    rec.discard()  # failed step: drop its partial accumulation

    # the NEXT step accounts cleanly — nesting and exclusive time intact
    with rec.phase("compute"):
        time.sleep(0.02)
        with rec.phase("h2d"):
            time.sleep(0.02)
    rec.step_done()
    snap = reg.snapshot()["tdl_step_phase_seconds"]
    series = {s["labels"]["phase"]: s for s in snap["series"]}
    assert series["compute"]["count"] == 1  # the failed step left NO sample
    assert series["h2d"]["count"] == 1
    assert series["h2d"]["sum"] >= 0.02
    # exclusive: compute excludes the nested h2d slice
    assert series["compute"]["sum"] < 0.04


def test_step_phase_discard_after_failed_step_leaves_histograms_untouched():
    reg = MetricsRegistry()
    rec = StepPhaseRecorder(registry=reg)
    with rec.phase("input"):
        time.sleep(0.005)
    rec.step_done()  # one good step
    before = reg.snapshot()["tdl_step_phase_seconds"]
    with pytest.raises(ValueError):
        with rec.phase("input"):
            raise ValueError("iterator exploded")
    rec.discard()
    after = reg.snapshot()["tdl_step_phase_seconds"]
    assert after == before  # discard() observed nothing
    assert rec.summary()["steps"] == 1  # the failed step never counted


def test_parallel_trainer_emits_phases_and_step_wall():
    import jax
    from jax.sharding import Mesh

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.monitoring import get_registry
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    from tests.mp_workers import _global_batch, _toy_net

    reg = get_registry()
    base_phase = reg.get("tdl_step_phase_seconds")
    base_counts = ({s["labels"]["phase"]: s["count"]
                    for s in base_phase.snapshot()["series"]}
                   if base_phase else {})
    net = _toy_net()
    trainer = ParallelTrainer(net, Mesh(np.array(jax.devices()[:2]), ("data",)))
    x, y = _global_batch(0)
    trainer.fit([DataSet(x, y), DataSet(x, y), DataSet(x, y)])
    counts = {s["labels"]["phase"]: s["count"]
              for s in reg.get("tdl_step_phase_seconds").snapshot()["series"]}
    assert counts.get("compute", 0) - base_counts.get("compute", 0) == 3
    assert counts.get("input", 0) > base_counts.get("input", 0)
    wall = reg.get("tdl_step_wall_seconds").snapshot()["series"]
    assert any(s["labels"]["trainer"] == "ParallelTrainer" and s["count"] >= 2
               for s in wall)


# ------------------------------------------ supervisor failure bookkeeping


def _offline_supervisor(tmp_path, registry):
    from deeplearning4j_tpu.parallel.supervisor import GangSupervisor

    return GangSupervisor("x:y", n_processes=2, registry=registry,
                          workdir=str(tmp_path / "gang"))


def test_supervisor_last_failure_info_gauge(tmp_path):
    from deeplearning4j_tpu.parallel.supervisor import GangEvent

    reg = MetricsRegistry()
    sup = _offline_supervisor(tmp_path, reg)
    sup._note_failure(GangEvent(time.monotonic(), "crash", 0, (1,), 7))
    snap = reg.snapshot()["tdl_gang_last_failure_info"]
    assert len(snap["series"]) == 1
    assert snap["series"][0]["labels"] == {"reason": "crash", "rank": "1",
                                          "iteration": "7"}
    assert sup.last_failure["reason"] == "crash"
    # a second failure REPLACES the series (one-series info gauge)
    sup.restarts = 1
    sup._note_failure(GangEvent(time.monotonic(), "hang", 1, (0,), 9))
    snap = reg.snapshot()["tdl_gang_last_failure_info"]
    assert len(snap["series"]) == 1
    assert snap["series"][0]["labels"]["reason"] == "hang"
    assert snap["series"][0]["value"] == 1  # restarts at failure time


def test_supervisor_postmortem_merges_spools(tmp_path):
    from deeplearning4j_tpu.parallel.supervisor import GangEvent

    sup = _offline_supervisor(tmp_path, MetricsRegistry())
    sup.flight_dir = str(tmp_path / "flight")
    for rank in (0, 1):
        rec = FlightRecorder(proc=f"rank{rank}", directory=sup.flight_dir,
                             interval=0.0)
        rec.record("step_begin", iteration=6)
        rec.record("step_end", iteration=6, loss=0.5)
    FlightRecorder(proc="rank1", directory=sup.flight_dir,
                   interval=0.0).record("step_begin", iteration=7)
    failure = GangEvent(time.monotonic(), "crash", 0, (1,), 7)
    sup._note_failure(failure)
    path = sup._write_postmortem(failure)
    with open(path) as f:
        pm = json.load(f)
    assert pm["classification"] == "crash" and pm["iteration"] == 7
    ts = [e["t"] for e in pm["events"]]
    assert ts == sorted(ts)  # monotonic merged timeline
    assert set(pm["procs"]) == {"rank0", "rank1", "supervisor"}
    r1 = [e for e in pm["events"] if e["proc"] == "rank1"]
    assert any(e["kind"] == "step_begin" and e["iteration"] == 7 for e in r1)
    assert any(e["kind"] == "gang_failure" for e in pm["events"])


# -------------------------------------- registry across spawn (satellite 4)


def _spawn_probe(out_path, spool_dir):
    """Child side: report registry contents at entry + spool path."""
    from deeplearning4j_tpu.monitoring.aggregate import MetricsSpooler
    from deeplearning4j_tpu.monitoring.registry import get_registry

    reg = get_registry()
    names_at_start = reg.names()
    reg.counter("tdl_spawn_child_total").inc()
    spooler = MetricsSpooler(spool_dir, proc="spawncheck", registry=reg,
                             interval=0.0)
    spooler.spool(force=True)
    with open(out_path, "w") as f:
        json.dump({"names_at_start": names_at_start,
                   "spool_path": spooler.path}, f)


def test_registry_clean_and_spool_collision_free_across_spawn(tmp_path):
    from deeplearning4j_tpu.monitoring import get_registry

    parent_reg = get_registry()
    parent_reg.counter("tdl_spawn_parent_total").inc(41)
    spool_dir = str(tmp_path / "spool")
    parent_spooler = MetricsSpooler(spool_dir, proc="spawncheck",
                                    registry=parent_reg, interval=0.0)
    parent_spooler.spool(force=True)
    out = str(tmp_path / "child.json")
    ctx = multiprocessing.get_context("spawn")
    p = ctx.Process(target=_spawn_probe, args=(out, spool_dir))
    p.start()
    p.join(timeout=120)
    assert p.exitcode == 0
    with open(out) as f:
        child = json.load(f)
    # spawn gives the child a FRESH interpreter: no inherited counts
    assert "tdl_spawn_parent_total" not in child["names_at_start"]
    # same proc label + same dir, different pid → structurally distinct files
    assert child["spool_path"] != parent_spooler.path
    assert os.path.exists(child["spool_path"])
    assert os.path.exists(parent_spooler.path)
    # and the merge keeps exactly one (the newest) for the shared proc label
    assert len(aggregate.read_spools(spool_dir)) == 1


# --------------------------------------------- wall-clock AST lint (sat 1)


def _dotted(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def test_no_wall_clock_in_timing_paths():
    """Repo lint (ISSUE 7 satellite): ``time.time()`` steps backwards under
    NTP, so durations/deadlines must use ``time.perf_counter()`` /
    ``time.monotonic()``. Remaining ``time.time()`` sites are event
    timestamps and must say so with a ``# wallclock-ok:`` comment. Module
    aliases (``import time as _time``) are resolved per file so aliasing
    can't structurally bypass the lint."""
    root = ROOT / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        time_aliases = {"time"} | {
            a.asname for node in ast.walk(tree) if isinstance(node, ast.Import)
            for a in node.names if a.name == "time" and a.asname}
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "time"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in time_aliases
                    and "wallclock-ok" not in lines[node.lineno - 1]):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "time.time() in library code without a `# wallclock-ok:` "
        "justification (wall clock steps backwards under NTP — use "
        f"perf_counter/monotonic for anything timed): {offenders}")


# ----------------------------------------- bench telemetry check (sat 6)


def test_documented_bench_families_parse():
    import bench

    fams = bench.documented_bench_families()
    assert "tdl_step_phase_seconds" in fams
    assert "tdl_inference_batch_size" in fams
    assert "tdl_gang_restarts_total" not in fams  # marked "no": gangs don't run in bench
    assert all(f.startswith("tdl_") for f in fams)


def test_check_telemetry_flags_dead_families():
    import bench

    live_hist = {"type": "histogram", "series": [{"count": 3, "sum": 0.1}]}
    dead_hist = {"type": "histogram", "series": [{"count": 0, "sum": 0.0}]}
    drained_gauge = {"type": "gauge", "series": [{"labels": {}, "value": 0}]}
    out = {"telemetry": {"metrics": {"tdl_a": live_hist, "tdl_b": dead_hist,
                                     "tdl_c": drained_gauge}}}
    assert bench.check_telemetry(out, ["tdl_a", "tdl_c"]) == []
    # dead histogram, registered-but-unobserved, and absent all flag
    assert bench.check_telemetry(out, ["tdl_a", "tdl_b", "tdl_missing"]) == [
        "tdl_b", "tdl_missing"]


def test_documented_catalog_matches_declared_families():
    """Every `tdl_*` family string declared in library code must have a
    catalog row in docs/OBSERVABILITY.md — the doc stays the single source
    of truth as families are added."""
    doc = (ROOT / "docs" / "OBSERVABILITY.md").read_text()
    documented = set(re.findall(r"`(tdl_[a-z0-9_]+)`", doc))
    decl = re.compile(
        r'\.(?:counter|gauge|histogram)\(\s*["\'](tdl_[a-z0-9_]+)["\']')
    declared = set()
    for path in sorted((ROOT / "deeplearning4j_tpu").rglob("*.py")):
        declared.update(decl.findall(path.read_text()))
    assert len(declared) > 30  # the scan found the real declaration sites
    missing = declared - documented
    assert not missing, (
        f"metric families declared in code but missing from "
        f"docs/OBSERVABILITY.md's catalog: {sorted(missing)}")


# ------------------------------------------------------------- slow tier
# Real 2-process gangs under GangSupervisor (~30-60s each): the ISSUE 7
# acceptance runs. Slow-marked like the rest of the multiprocess tier.


@pytest.mark.slow
def test_aggregated_scrape_two_rank_gang_with_straggler(tmp_path):
    """Acceptance: one aggregated /metrics scrape shows the same family with
    distinct rank labels for both ranks, and an injected slow_ckpt_io on
    rank 1 surfaces as a nonzero straggler-skew gauge."""
    from deeplearning4j_tpu.parallel import GangSupervisor
    from deeplearning4j_tpu.ui.server import UIServer

    # 10 steps so the per-step 0.4s checkpoint sleep on rank 1 dominates the
    # (rank-symmetric) first-step compile inside the step-wall means
    env = {"TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MP_CKPT": str(tmp_path / "ckpt"),
           "TDL_MP_STEPS": "10",
           "TDL_MATMUL_PRECISION": "float32",
           "TDL_FAULT_SPEC": "slow_ckpt_io@value=0.4,rank=1",
           "TDL_METRICS_SPOOL_INTERVAL": "0",
           "TDL_FLIGHT_INTERVAL": "0"}
    os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
    reg = MetricsRegistry()
    sup = GangSupervisor(f"{WORKERS}:observability_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, startup_grace=300.0,
                         registry=reg)
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"

    ui = UIServer(port=0)
    try:
        ui.attach_spool_dir(sup.spool_dir, local_proc="supervisor")
        url = f"http://127.0.0.1:{ui.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode()
    finally:
        ui.stop()
    parsed = _parse_prometheus(text)  # strict: a real scraper must accept it
    walls = parsed["tdl_step_wall_seconds_count"]
    per_rank = {dict(k).get("rank"): v for k, v in walls.items()}
    # superset: the scraping pytest process's own registry may contribute a
    # rank-less series when an earlier test ran a trainer (see the fast
    # merged-metrics test) — the gang's two spooled ranks are the assertion
    assert {"0", "1"} <= set(per_rank)
    assert all(v >= 2 for r, v in per_rank.items() if r in ("0", "1"))
    # rank 1 sleeps 0.4s in every checkpoint save → its iteration-to-
    # iteration wall dominates and the derived skew gauge is well over 1
    assert parsed["tdl_step_time_skew_ratio"][()] > 1.3
    assert parsed["tdl_step_time_slowest_rank"][()] == 1
    # per-rank means back the ratio up
    means = parsed["tdl_step_time_mean_seconds"]
    assert means[(("rank", "1"),)] > means[(("rank", "0"),)]


@pytest.mark.slow
def test_postmortem_from_crash_injected_gang(tmp_path):
    """Acceptance: a crash-injected supervised gang leaves a postmortem.json
    whose merged event stream is monotonically ordered and contains step
    events from every rank INCLUDING the crashed rank's final step."""
    from deeplearning4j_tpu.parallel import GangSupervisor

    env = {"TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MP_CKPT": str(tmp_path / "ckpt"),
           "TDL_MP_STEPS": "10",
           "TDL_MP_CKPT_EVERY": "2",
           "TDL_MATMUL_PRECISION": "float32",
           "TDL_FAULT_SPEC": "crash@iter=7,rank=1",
           "TDL_FLIGHT_INTERVAL": "0",
           "TDL_METRICS_SPOOL_INTERVAL": "0"}
    os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
    sup = GangSupervisor(f"{WORKERS}:supervised_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, startup_grace=300.0,
                         backoff_base=0.1, kill_grace=1.0, max_restarts=3,
                         registry=MetricsRegistry())
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1

    assert os.path.exists(sup.postmortem_path)
    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert pm["classification"] == "crash"
    assert 1 in pm["ranks"] and pm["iteration"] == 7
    ts = [e["t"] for e in pm["events"]]
    assert ts == sorted(ts)  # monotonic-clock-ordered merged stream
    assert {"rank0", "rank1", "supervisor"} <= set(pm["procs"])
    by_proc = {}
    for e in pm["events"]:
        by_proc.setdefault(e["proc"], []).append(e)
    # step events from every rank, including the victim's final step (the
    # step_begin at the crash iteration was flushed by the fault injector)
    for proc in ("rank0", "rank1"):
        assert any(e["kind"] == "step_begin" for e in by_proc[proc]), proc
    assert any(e["kind"] == "step_begin" and e.get("iteration") == 7
               for e in by_proc["rank1"])
    assert any(e["kind"] == "fault_injected" and e.get("fault") == "crash"
               for e in by_proc["rank1"])
    assert any(e["kind"] == "gang_failure" for e in by_proc["supervisor"])
    # checkpoint breadcrumbs made it too (save every 2 steps)
    assert any(e["kind"] == "ckpt_save" for e in pm["events"])
