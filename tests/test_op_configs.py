"""Per-op config beans (VERDICT r4 J3 tail): validation + lowering parity
vs direct registry calls — ref: org.nd4j.linalg.api.ops.impl.layers.
convolution.config.* / recurrent.config.LSTMConfiguration."""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.op_configs import (
    Conv1DConfig,
    Conv2DConfig,
    Conv3DConfig,
    DeConv2DConfig,
    DeConv3DConfig,
    LocalResponseNormalizationConfig,
    LSTMConfiguration,
    OpConfigError,
    Pooling2DConfig,
    Pooling3DConfig,
)
from deeplearning4j_tpu.autodiff.ops_registry import OPS

R = np.random.RandomState(2)
X = R.randn(2, 3, 8, 8).astype(np.float32)
W = (R.randn(4, 3, 3, 3) * 0.3).astype(np.float32)


class TestValidation:
    def test_positive_fields_enforced(self):
        with pytest.raises(OpConfigError, match="kH"):
            Conv2DConfig(kH=0).validate()
        with pytest.raises(OpConfigError, match="pW"):
            Conv2DConfig(kH=3, kW=3, pW=-1).validate()
        with pytest.raises(OpConfigError, match="MAX"):
            Pooling2DConfig(type="median").validate()
        with pytest.raises(OpConfigError, match="clippingCellValue"):
            LSTMConfiguration(clippingCellValue=-1.0).validate()

    def test_peephole_requires_weights(self):
        cfg = LSTMConfiguration(peepHole=True)
        with pytest.raises(OpConfigError, match="peepHole"):
            cfg.execute_cell(np.zeros((1, 2), np.float32),
                             np.zeros((1, 3), np.float32),
                             np.zeros((1, 3), np.float32),
                             np.zeros((2, 12), np.float32),
                             np.zeros((3, 12), np.float32),
                             np.zeros(12, np.float32))

    def test_to_dict_roundtrip(self):
        cfg = Conv2DConfig(kH=3, kW=3, sH=2, sW=2, isSameMode=True)
        assert Conv2DConfig(**cfg.to_dict()) == cfg


class TestLowering:
    def test_conv2d_same_and_padded(self):
        same = Conv2DConfig(kH=3, kW=3, isSameMode=True).execute(X, W)
        np.testing.assert_allclose(
            np.asarray(same), np.asarray(OPS["conv2d"](X, W, padding="SAME")),
            rtol=1e-5)
        padded = Conv2DConfig(kH=3, kW=3, pH=1, pW=2).execute(X, W)
        np.testing.assert_allclose(
            np.asarray(padded),
            np.asarray(OPS["conv2d"](X, W, padding=[(1, 1), (2, 2)])),
            rtol=1e-5)

    def test_conv1d(self):
        x1 = X[:, :, :, 0].copy()
        w1 = (R.randn(5, 3, 3) * 0.3).astype(np.float32)
        out = Conv1DConfig(k=3, s=1, isSameMode=True).execute(x1, w1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(OPS["conv1d"](x1, w1, padding="SAME")),
            rtol=1e-5)

    def test_conv3d_bias_gate(self):
        x5 = R.randn(1, 2, 4, 4, 4).astype(np.float32)
        w5 = (R.randn(3, 2, 2, 2, 2) * 0.3).astype(np.float32)
        cfg = Conv3DConfig(kD=2, kH=2, kW=2, biasUsed=True, isSameMode=True)
        with pytest.raises(OpConfigError, match="bias"):
            cfg.execute(x5, w5)
        out = cfg.execute(x5, w5, np.ones(3, np.float32))
        assert np.asarray(out).shape == (1, 3, 4, 4, 4)

    def test_deconv_2d_3d(self):
        wt = (R.randn(3, 2, 2, 2) * 0.3).astype(np.float32)   # IOHW
        out = DeConv2DConfig(kH=2, kW=2, sH=2, sW=2).execute(X, wt)
        assert np.asarray(out).shape == (2, 2, 16, 16)
        x5 = R.randn(1, 2, 3, 3, 3).astype(np.float32)
        w5 = (R.randn(2, 2, 2, 2, 2) * 0.3).astype(np.float32)  # IODHW
        out3 = DeConv3DConfig(kD=2, kH=2, kW=2, sD=2, sH=2, sW=2).execute(x5, w5)
        assert np.asarray(out3).shape == (1, 2, 6, 6, 6)

    @pytest.mark.parametrize("ptype,op", [("MAX", "max_pool2d"),
                                          ("AVG", "avg_pool2d")])
    def test_pooling2d(self, ptype, op):
        out = Pooling2DConfig(type=ptype).execute(X)
        np.testing.assert_allclose(np.asarray(out), np.asarray(OPS[op](X)),
                                   rtol=1e-6)

    def test_pooling2d_pnorm_extra(self):
        out = Pooling2DConfig(type="PNORM", extra=3.0).execute(X)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(OPS["pnormpool2d"](X, p=3.0)),
                                   rtol=1e-5)

    def test_pooling3d(self):
        x5 = R.randn(1, 2, 4, 4, 4).astype(np.float32)
        out = Pooling3DConfig(type="AVG").execute(x5)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(OPS["avg_pool3d"](x5)), rtol=1e-6)

    def test_lrn(self):
        out = LocalResponseNormalizationConfig(depth=5, alpha=1e-3).execute(X)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(OPS["lrn"](X, depth_radius=2, alpha=1e-3, beta=0.75,
                                  bias=1.0)), rtol=1e-5)

    def test_lstm_configuration_cell(self):
        x = R.randn(2, 3).astype(np.float32)
        h0 = np.zeros((2, 4), np.float32)
        c0 = np.zeros((2, 4), np.float32)
        wx = (R.randn(3, 16) * 0.4).astype(np.float32)
        wh = (R.randn(4, 16) * 0.4).astype(np.float32)
        b = np.zeros(16, np.float32)
        h, c = LSTMConfiguration(forgetBias=1.0).execute_cell(x, h0, c0, wx, wh, b)
        h2, c2 = OPS["lstm_block_cell"](x, h0, c0, wx, wh, b, forget_bias=1.0)
        np.testing.assert_allclose(np.asarray(h), np.asarray(h2), rtol=1e-6)
        # clipping bounds the cell state
        hcl, ccl = LSTMConfiguration(clippingCellValue=0.01).execute_cell(
            x, h0, c0, wx, wh, b)
        assert float(np.max(np.abs(np.asarray(ccl)))) <= 0.01 + 1e-7
