"""Arbiter hyperparameter-search tests (SURVEY §2.7 A1/A2).

ISSUE 20 satellites: exact grid-exhaustion semantics, concurrent /
out-of-order score-report safety for the genetic generator, seeded
determinism for all three generators, log-scale continuous bounds, and
genetic mutation clamping."""

import concurrent.futures
import itertools
import math
import random

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    GeneratorExhausted,
    GeneticSearchCandidateGenerator,
    GridSearchCandidateGenerator,
    IntegerParameterSpace,
    LocalOptimizationRunner,
    MaxCandidatesCondition,
    MultiLayerSpace,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.arbiter.spaces import LayerSpace
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_parameter_spaces():
    c = ContinuousParameterSpace(0.001, 0.1, log_scale=True)
    assert 0.001 <= c.value(0.0) < c.value(0.999) <= 0.1
    i = IntegerParameterSpace(4, 8)
    assert set(i.grid_points(10)) == {4, 5, 6, 7, 8}
    d = DiscreteParameterSpace("relu", "tanh")
    assert d.value(0.0) == "relu" and d.value(0.99) == "tanh"


def test_grid_generator_enumerates_product():
    gen = GridSearchCandidateGenerator(
        {"a": DiscreteParameterSpace(1, 2), "b": DiscreteParameterSpace("x", "y", "z")})
    cands = []
    while gen.has_more():
        cands.append(tuple(gen.next_candidate().values()))
    assert len(cands) == 6 and len(set(cands)) == 6


def test_runner_finds_quadratic_minimum():
    spaces = {"x": ContinuousParameterSpace(-5, 5), "y": ContinuousParameterSpace(-5, 5)}
    runner = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=3),
        lambda c: (c["x"] - 1.0) ** 2 + (c["y"] + 2.0) ** 2,
        [MaxCandidatesCondition(200)])
    res = runner.execute()
    assert res.best_score < 0.5
    assert abs(res.best_candidate["x"] - 1.0) < 1.0
    assert abs(res.best_candidate["y"] + 2.0) < 1.0


def test_genetic_beats_random_on_budget():
    spaces = {f"x{i}": ContinuousParameterSpace(-3, 3) for i in range(4)}

    def score(c):
        return sum((v - 1.0) ** 2 for v in c.values())

    budget = 120
    res_g = LocalOptimizationRunner(
        GeneticSearchCandidateGenerator(spaces, population=12, seed=5),
        score, [MaxCandidatesCondition(budget)]).execute()
    res_r = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=5),
        score, [MaxCandidatesCondition(budget)]).execute()
    assert res_g.best_score <= res_r.best_score * 1.5  # GA at least competitive
    assert res_g.best_score < 1.0


def test_multilayer_space_search():
    """End-to-end: search layer width + lr on a tiny classification task."""
    rs = np.random.RandomState(0)
    X = rs.randn(96, 6).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[np.argmax(X[:, :3], 1)]

    mls = (MultiLayerSpace.Builder()
           .seed(7)
           .learning_rate(ContinuousParameterSpace(1e-3, 1e-1, log_scale=True))
           .add_layer(LayerSpace(DenseLayer, n_in=6,
                                 n_out=IntegerParameterSpace(4, 24),
                                 activation="tanh"))
           .add_layer(LayerSpace(OutputLayer, n_out=3, activation="softmax",
                                 loss="mcxent"))
           .build())
    spaces = mls.param_spaces()
    assert set(spaces) == {"learning_rate", "layer0.n_out"}

    def score(candidate):
        net = MultiLayerNetwork(mls.materialize(candidate)).init()
        for _ in range(8):
            net._fit_batch(DataSet(X, Y))
        return net.score_

    res = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=1), score,
        [MaxCandidatesCondition(5)]).execute()
    assert np.isfinite(res.best_score)
    assert 4 <= res.best_candidate["layer0.n_out"] <= 24
    assert len(res.all_results) == 5


# --------------------------------------- ISSUE 20 satellite: generator safety


SPACES = {
    "lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True),
    "hidden": IntegerParameterSpace(4, 32),
    "act": DiscreteParameterSpace("relu", "tanh"),
}


def _strip(c):
    return {k: v for k, v in c.items() if k != "__id__"}


def test_grid_exhaustion_is_exact_and_sticky():
    """has_more() counts candidates that will actually be handed out;
    an over-draw raises the typed GeneratorExhausted, and exhaustion never
    un-sticks."""
    gen = GridSearchCandidateGenerator(
        {"a": DiscreteParameterSpace(1, 2),
         "b": DiscreteParameterSpace("x", "y", "z")})
    seen = []
    for _ in range(6):
        assert gen.has_more()
        seen.append(tuple(gen.next_candidate().values()))
    assert len(set(seen)) == 6
    assert not gen.has_more()
    with pytest.raises(GeneratorExhausted):
        gen.next_candidate()
    assert not gen.has_more()  # the failed draw didn't revive it


def test_grid_folds_duplicate_combos_before_counting():
    """A coarse discretization of a small integer axis emits duplicate grid
    points; has_more() must not promise a phantom trailing duplicate."""
    gen = GridSearchCandidateGenerator(
        {"n": IntegerParameterSpace(1, 2),
         "b": DiscreteParameterSpace("x", "y")},
        discretization_count=5)
    out = []
    while gen.has_more():
        out.append(tuple(sorted(gen.next_candidate().items())))
    assert len(out) == 4  # 2 distinct n values x 2 b values, no repeats
    assert len(set(out)) == len(out)


def test_grid_concurrent_draws_hand_out_distinct_candidates():
    gen = GridSearchCandidateGenerator(
        {"a": DiscreteParameterSpace(*range(8)),
         "b": DiscreteParameterSpace(*range(8))})

    def draw_all():
        got = []
        while True:
            try:
                got.append(tuple(sorted(gen.next_candidate().items())))
            except GeneratorExhausted:
                return got

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        chunks = list(ex.map(lambda _: draw_all(), range(8)))
    flat = list(itertools.chain.from_iterable(chunks))
    assert len(flat) == 64
    assert len(set(flat)) == 64  # no combo handed to two callers


def test_generators_are_seed_deterministic():
    for cls, kwargs in (
            (RandomSearchGenerator, {}),
            (GridSearchCandidateGenerator, {"discretization_count": 3}),
            (GeneticSearchCandidateGenerator, {"population": 4})):
        a = cls(SPACES, seed=11, **kwargs)
        b = cls(SPACES, seed=11, **kwargs)
        other = cls(SPACES, seed=12, **kwargs)
        sa, sb, so = [], [], []
        for i in range(8):
            ca, cb, co = (g.next_candidate() for g in (a, b, other))
            sa.append(_strip(ca)), sb.append(_strip(cb)), so.append(_strip(co))
            # feed the adaptive generator identical scores so its
            # post-seeding draws stay comparable
            for g, c in ((a, ca), (b, cb), (other, co)):
                g.report_score(c, float(i % 3))
        assert sa == sb
        if cls is not GridSearchCandidateGenerator:  # grid ignores its seed
            assert sa != so


def test_log_scale_continuous_respects_bounds():
    s = ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)
    lo, hi = s.value(0.0), s.value(1.0 - 1e-12)
    assert lo == pytest.approx(1e-4)
    assert hi <= 1e-1 and hi == pytest.approx(1e-1, rel=1e-6)
    mid = s.value(0.5)  # geometric midpoint, not arithmetic
    assert mid == pytest.approx(math.sqrt(1e-4 * 1e-1), rel=1e-6)
    rs = np.random.RandomState(0)
    for u in rs.rand(200):
        assert 1e-4 <= s.value(float(u)) <= 1e-1
    for u in rs.rand(64):
        assert 4 <= IntegerParameterSpace(4, 32).value(float(u)) <= 32


def test_genetic_mutation_stays_inside_space_bounds():
    """Post-seeding children are crossover+mutation in u-space; the clip
    must keep every materialized value inside its space's bounds."""
    gen = GeneticSearchCandidateGenerator(
        SPACES, population=4, mutation_prob=1.0, mutation_sigma=5.0, seed=2)
    for i in range(4):
        gen.report_score(gen.next_candidate(), float(i))
    for _ in range(64):
        c = _strip(gen.next_candidate())
        assert 1e-4 <= c["lr"] <= 1e-1
        assert 4 <= c["hidden"] <= 32
        assert c["act"] in ("relu", "tanh")


def _drain_deterministic_tail(gen):
    return [_strip(gen.next_candidate()) for _ in range(12)]


def test_genetic_report_order_does_not_change_stream():
    """Any permutation of the same (candidate, score) reports converges the
    scored pool to the same state, so the post-seeding candidate stream
    under a fixed seed is identical regardless of completion order."""
    def seeded(order_seed):
        gen = GeneticSearchCandidateGenerator(SPACES, population=6, seed=9)
        cands = [gen.next_candidate() for _ in range(6)]
        reports = [(c, float(i % 4)) for i, c in enumerate(cands)]
        random.Random(order_seed).shuffle(reports)
        for c, s in reports:
            gen.report_score(c, s)
        return _drain_deterministic_tail(gen)

    ref = seeded(0)
    for order_seed in (1, 2, 3):
        assert seeded(order_seed) == ref


def test_genetic_report_is_concurrent_safe_and_idempotent():
    gen = GeneticSearchCandidateGenerator(SPACES, population=8, seed=9)
    cands = [gen.next_candidate() for _ in range(8)]
    reports = [(c, float(i % 4)) for i, c in enumerate(cands)]
    # duplicates + an unknown-id report must be ignored, not double-counted
    reports += reports[:3]
    reports.append(({"__id__": 10_000, "lr": 1e-3}, 0.0))
    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        list(ex.map(lambda r: gen.report_score(*r), reports))
    assert len(gen._scored) == 8
    ref = GeneticSearchCandidateGenerator(SPACES, population=8, seed=9)
    for i, c in enumerate([ref.next_candidate() for _ in range(8)]):
        ref.report_score(c, float(i % 4))
    assert _drain_deterministic_tail(gen) == _drain_deterministic_tail(ref)
