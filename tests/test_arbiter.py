"""Arbiter hyperparameter-search tests (SURVEY §2.7 A1/A2)."""

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    GeneticSearchCandidateGenerator,
    GridSearchCandidateGenerator,
    IntegerParameterSpace,
    LocalOptimizationRunner,
    MaxCandidatesCondition,
    MultiLayerSpace,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.arbiter.spaces import LayerSpace
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_parameter_spaces():
    c = ContinuousParameterSpace(0.001, 0.1, log_scale=True)
    assert 0.001 <= c.value(0.0) < c.value(0.999) <= 0.1
    i = IntegerParameterSpace(4, 8)
    assert set(i.grid_points(10)) == {4, 5, 6, 7, 8}
    d = DiscreteParameterSpace("relu", "tanh")
    assert d.value(0.0) == "relu" and d.value(0.99) == "tanh"


def test_grid_generator_enumerates_product():
    gen = GridSearchCandidateGenerator(
        {"a": DiscreteParameterSpace(1, 2), "b": DiscreteParameterSpace("x", "y", "z")})
    cands = []
    while gen.has_more():
        cands.append(tuple(gen.next_candidate().values()))
    assert len(cands) == 6 and len(set(cands)) == 6


def test_runner_finds_quadratic_minimum():
    spaces = {"x": ContinuousParameterSpace(-5, 5), "y": ContinuousParameterSpace(-5, 5)}
    runner = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=3),
        lambda c: (c["x"] - 1.0) ** 2 + (c["y"] + 2.0) ** 2,
        [MaxCandidatesCondition(200)])
    res = runner.execute()
    assert res.best_score < 0.5
    assert abs(res.best_candidate["x"] - 1.0) < 1.0
    assert abs(res.best_candidate["y"] + 2.0) < 1.0


def test_genetic_beats_random_on_budget():
    spaces = {f"x{i}": ContinuousParameterSpace(-3, 3) for i in range(4)}

    def score(c):
        return sum((v - 1.0) ** 2 for v in c.values())

    budget = 120
    res_g = LocalOptimizationRunner(
        GeneticSearchCandidateGenerator(spaces, population=12, seed=5),
        score, [MaxCandidatesCondition(budget)]).execute()
    res_r = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=5),
        score, [MaxCandidatesCondition(budget)]).execute()
    assert res_g.best_score <= res_r.best_score * 1.5  # GA at least competitive
    assert res_g.best_score < 1.0


def test_multilayer_space_search():
    """End-to-end: search layer width + lr on a tiny classification task."""
    rs = np.random.RandomState(0)
    X = rs.randn(96, 6).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[np.argmax(X[:, :3], 1)]

    mls = (MultiLayerSpace.Builder()
           .seed(7)
           .learning_rate(ContinuousParameterSpace(1e-3, 1e-1, log_scale=True))
           .add_layer(LayerSpace(DenseLayer, n_in=6,
                                 n_out=IntegerParameterSpace(4, 24),
                                 activation="tanh"))
           .add_layer(LayerSpace(OutputLayer, n_out=3, activation="softmax",
                                 loss="mcxent"))
           .build())
    spaces = mls.param_spaces()
    assert set(spaces) == {"learning_rate", "layer0.n_out"}

    def score(candidate):
        net = MultiLayerNetwork(mls.materialize(candidate)).init()
        for _ in range(8):
            net._fit_batch(DataSet(X, Y))
        return net.score_

    res = LocalOptimizationRunner(
        RandomSearchGenerator(spaces, seed=1), score,
        [MaxCandidatesCondition(5)]).execute()
    assert np.isfinite(res.best_score)
    assert 4 <= res.best_candidate["layer0.n_out"] <= 24
    assert len(res.all_results) == 5
