"""Fault-isolated trial fleets (ISSUE 20): PBT/ASHA meta-supervisor.

Fast tier drives ``TrialFleet``'s scheduler with in-process runners —
verdicts, quarantine/straggler/clone decision paths, checkpoint cloning
against real lineages (including a corrupted clone source falling back to
an older generation), mid-sweep kill + resume to identical verdicts, the
``tdl_trial_*`` metric families, the spool score reader, and the
trial-terminal-decision AST lint (with a planted-offender self-test).

Slow tier runs real trial gangs through ``GangSupervisor``: a chaos sweep
with injected worker crashes and a deliberately corrupted clone source,
and a SIGKILLed fleet CLI resuming mid-rung.
"""

import ast
import json
import os
import pathlib
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                        DiscreteParameterSpace,
                                        GridSearchCandidateGenerator,
                                        IntegerParameterSpace,
                                        RandomSearchGenerator, TrialFleet,
                                        TrialStraggler, spooled_scores)
from deeplearning4j_tpu.arbiter import fleet as fleet_mod
from deeplearning4j_tpu.common import faults
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.monitoring.trial import (TRIAL_STATES,
                                                 set_trial_state,
                                                 trial_metrics)
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serde.checkpoint import (CheckpointVerifyError,
                                                 TrainingCheckpointer,
                                                 clone_generation,
                                                 lineage_state)

ROOT = pathlib.Path(__file__).resolve().parent.parent

SPACES = {"lr": ContinuousParameterSpace(1e-4, 1e-1, log_scale=True),
          "hidden": IntegerParameterSpace(4, 32)}


def _score_runner(fn):
    """Adapter: a pure f(hparams, rung_target) -> score as a fleet runner."""

    def runner(slot, target_iter, timeout_s):
        return fn(slot.hparams, target_iter)

    return runner


def _lr_score(hp, target):
    # deterministic, lr-sensitive, improves with budget — enough structure
    # for ASHA cuts to be meaningful without training anything
    import math

    base = 1.0 - abs(math.log10(hp["lr"]) + 2.0) / 4.0
    return base * (1.0 - 1.0 / (2.0 + target))


def _fleet(tmp_path, runner, *, name="f", reg=None, **kw):
    kw.setdefault("n_trials", 6)
    kw.setdefault("rungs", (2, 4, 8))
    kw.setdefault("seed", 5)
    kw.setdefault("rung_timeout_s", 30.0)
    kw.setdefault("max_concurrent", 3)
    gen = kw.pop("generator", None) or RandomSearchGenerator(SPACES, seed=3)
    return TrialFleet(gen, runner, workdir=str(tmp_path / name),
                      registry=reg or MetricsRegistry(), **kw)


def _journal_kinds(fleet):
    return [r["kind"] for r in fleet.state["journal"]]


def _fleet_events(fleet):
    spools = flight.read_spools(fleet.flight_dir)
    return [e for s in spools for e in s.get("events", [])]


class TestFleetScheduler:
    def test_sweep_promotes_a_winner_with_audited_rungs(self, tmp_path):
        reg = MetricsRegistry()
        fleet = _fleet(tmp_path, _score_runner(_lr_score), reg=reg)
        try:
            winner = fleet.run()
        finally:
            fleet.close()
        assert winner["trial"] in fleet.trials
        assert fleet.trials[winner["trial"]].status == "winner"
        # every rung reached a journaled verdict, and the cohort shrank by
        # the reduction factor at each barrier
        verdicts = fleet.state["verdicts"]
        assert set(verdicts) == {"0", "1", "2"}
        assert len(verdicts["0"]["promoted"]) == 3
        assert len(verdicts["1"]["promoted"]) == 2
        # decisions are on the flight spool too (the audit trail contract)
        kinds = {e["kind"] for e in _fleet_events(fleet)}
        assert {"trial_spawn", "trial_demote", "trial_rung_promote",
                "trial_promote"} <= kinds
        # and in the metrics: exactly one winner state, promotions counted
        snap = reg.snapshot()
        winners = [s for s in snap["tdl_trial_state"]["series"]
                   if s["labels"]["state"] == "winner" and s["value"] == 1.0]
        assert len(winners) == 1
        assert snap["tdl_trial_rung_promotions_total"]["series"][0]["value"] >= 3
        assert snap["tdl_fleet_disk_bytes"]["series"][0]["value"] > 0

    def test_run_is_reentrant_after_completion(self, tmp_path):
        fleet = _fleet(tmp_path, _score_runner(_lr_score))
        try:
            first = fleet.run()
            assert fleet.run() == first  # journaled winner, no re-run
        finally:
            fleet.close()

    def test_crashing_trial_is_quarantined_and_sweep_survives(self, tmp_path):
        reg = MetricsRegistry()
        calls = {}

        def runner(slot, target, timeout_s):
            calls[slot.trial_id] = calls.get(slot.trial_id, 0) + 1
            if slot.trial_id == "t00":
                raise RuntimeError("boom (injected)")
            return _lr_score(slot.hparams, target)

        fleet = _fleet(tmp_path, runner, reg=reg, trial_max_restarts=2,
                       backoff_base_s=0.01, backoff_max_s=0.02)
        try:
            winner = fleet.run()
        finally:
            fleet.close()
        assert winner["trial"] != "t00"
        t0 = fleet.trials["t00"]
        assert t0.status == "quarantined"
        assert t0.quarantine_reason == "crash_budget"
        assert calls["t00"] == 3  # initial + trial_max_restarts retries
        ev = [e for e in _fleet_events(fleet)
              if e["kind"] == "trial_quarantine"]
        assert ev and ev[0]["trial"] == "t00" \
            and ev[0]["reason"] == "crash_budget"
        series = MetricsRegistry.snapshot(reg)["tdl_trial_quarantined_total"]
        assert {(s["labels"]["reason"], s["value"])
                for s in series["series"]} == {("crash_budget", 1.0)}

    def test_wedged_gang_quarantines_as_wedged(self, tmp_path):
        class Hung(RuntimeError):
            classification = "hang"

        def runner(slot, target, timeout_s):
            if slot.trial_id == "t01":
                raise Hung("gang died hanging")
            return _lr_score(slot.hparams, target)

        fleet = _fleet(tmp_path, runner, trial_max_restarts=1,
                       backoff_base_s=0.01, backoff_max_s=0.02)
        try:
            fleet.run()
        finally:
            fleet.close()
        assert fleet.trials["t01"].quarantine_reason == "wedged"

    def test_straggler_is_demoted_not_waited_for(self, tmp_path):
        started = time.monotonic()

        def runner(slot, target, timeout_s):
            if slot.trial_id == "t02":
                raise TrialStraggler("over rung deadline")
            return _lr_score(slot.hparams, target)

        fleet = _fleet(tmp_path, runner)
        try:
            winner = fleet.run()
        finally:
            fleet.close()
        assert time.monotonic() - started < 20.0
        assert winner["trial"] != "t02"
        assert fleet.trials["t02"].status == "demoted"
        demotes = [r for r in fleet.state["journal"] if r["kind"] == "demote"
                   and r["trial"] == "t02"]
        assert demotes and demotes[0]["reason"] == "straggler"
        # a straggler is NOT a crash: no restart burned, no quarantine
        assert fleet.trials["t02"].restarts == 0

    def test_timeout_classified_exception_also_demotes(self, tmp_path):
        class GangTimeout(RuntimeError):
            classification = "timeout"

        def runner(slot, target, timeout_s):
            if slot.trial_id == "t00":
                raise GangTimeout("rung budget exceeded")
            return _lr_score(slot.hparams, target)

        fleet = _fleet(tmp_path, runner)
        try:
            fleet.run()
        finally:
            fleet.close()
        assert fleet.trials["t00"].status == "demoted"

    def test_rung_deadline_demotes_inline_sleeper(self, tmp_path):
        """A runner that simply blows the wall-clock deadline is demoted by
        the NEXT budget check — the rung barrier stays bounded."""

        def runner(slot, target, timeout_s):
            if slot.trial_id == "t00":
                time.sleep(0.4)
                raise RuntimeError("crashed after eating the rung budget")
            return _lr_score(slot.hparams, target)

        fleet = _fleet(tmp_path, runner, rung_timeout_s=0.2,
                       trial_max_restarts=5, backoff_base_s=0.01)
        try:
            fleet.run()
        finally:
            fleet.close()
        t0 = fleet.trials["t00"]
        assert t0.status == "demoted"

    def test_all_trials_dead_raises_not_invents_winner(self, tmp_path):
        def runner(slot, target, timeout_s):
            raise RuntimeError("everything burns")

        fleet = _fleet(tmp_path, runner, n_trials=3, trial_max_restarts=0,
                       backoff_base_s=0.01)
        try:
            with pytest.raises(RuntimeError, match="no surviving"):
                fleet.run()
        finally:
            fleet.close()

    def test_generator_exhaustion_shrinks_sweep(self, tmp_path):
        gen = GridSearchCandidateGenerator(
            {"lr": DiscreteParameterSpace(1e-3, 1e-2),
             "hidden": DiscreteParameterSpace(8, 16)})
        fleet = _fleet(tmp_path, _score_runner(_lr_score), generator=gen,
                       n_trials=16)
        try:
            fleet.run()
        finally:
            fleet.close()
        assert len(fleet.trials) == 4  # the grid, not the ask


class TestFleetResume:
    def _reference(self, tmp_path, runner):
        ref = _fleet(tmp_path, runner, name="ref")
        try:
            ref.run()
        finally:
            ref.close()
        return ref

    def test_killed_mid_rung_resumes_to_identical_verdicts(self, tmp_path):
        class KilledMidRung(BaseException):
            """Out-of-band death: not an Exception, so no retry path."""

        run_counts = {}

        def make_runner(kill_at=None):
            def runner(slot, target, timeout_s):
                key = (slot.trial_id, target)
                run_counts[key] = run_counts.get(key, 0) + 1
                if kill_at == key:
                    raise KilledMidRung()
                return _lr_score(slot.hparams, target)

            return runner

        ref = self._reference(tmp_path, make_runner())
        ref_scored = dict(run_counts)

        run_counts.clear()
        # first incarnation dies when t01 reaches rung 1 — rung 0 verdict is
        # journaled, rung 1 is mid-flight
        fleet = _fleet(tmp_path, make_runner(kill_at=("t01", 4)),
                       name="killed", max_concurrent=1)
        with pytest.raises(KilledMidRung):
            fleet.run()
        fleet.close()
        pre_crash = {k for k, v in run_counts.items() if v}

        run_counts.clear()
        resumed = _fleet(tmp_path, make_runner(), name="killed")
        assert resumed.state["resumed"]
        try:
            winner = resumed.run()
        finally:
            resumed.close()
        # identical verdicts, winner and scores as the uninterrupted run
        assert resumed.state["verdicts"] == ref.state["verdicts"]
        assert winner["trial"] == ref.state["winner"]["trial"]
        assert winner["score"] == ref.state["winner"]["score"]
        for tid, ref_slot in ref.trials.items():
            assert resumed.trials[tid].scores == ref_slot.scores
        # journaled pre-crash scores were NOT re-run by the resume
        rerun = [k for k, v in run_counts.items()
                 if k in pre_crash and k != ("t01", 4)]
        assert not rerun, f"resume re-ran journaled trials: {rerun}"
        # and the union of both incarnations equals the reference's work
        assert pre_crash | set(run_counts) == set(ref_scored)

    def test_resume_skips_completed_rungs_entirely(self, tmp_path):
        fleet = _fleet(tmp_path, _score_runner(_lr_score), name="done")
        try:
            fleet.run()
        finally:
            fleet.close()

        def exploding(slot, target, timeout_s):
            raise AssertionError("a finished sweep must not run trials")

        again = _fleet(tmp_path, exploding, name="done")
        try:
            assert again.run()["trial"] == fleet.state["winner"]["trial"]
        finally:
            again.close()


# ------------------------------------------------------- checkpoint cloning


def _net(seed=5):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _trained_lineage(directory, steps=3, seed=5, keep_last=8):
    net = _net(seed)
    rs = np.random.RandomState(0)
    x = rs.randn(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]
    ck = TrainingCheckpointer(directory, async_write=False,
                              keep_last=keep_last)
    for _ in range(steps):
        net._fit_batch(DataSet(x, y))
        ck.save(net)
    return net


class TestCloneGeneration:
    def test_clone_lands_as_restorable_suffixed_sibling(self, tmp_path):
        src_net = _trained_lineage(str(tmp_path / "win"))
        # the loser has its OWN generation at the same iteration: the clone
        # must land as a suffixed sibling that outranks it on restore
        _trained_lineage(str(tmp_path / "lose"), seed=77)
        src_gen = lineage_state(str(tmp_path / "win"))["newest_committed"]
        got = clone_generation(os.path.join(str(tmp_path / "win"),
                                            "latest", src_gen),
                               str(tmp_path / "lose"))
        assert got["generation"] != src_gen  # suffixed, not overwritten
        assert got["generation"].startswith(src_gen)
        assert got["iteration"] == int(src_net.iteration)
        restored = _net(seed=1)
        assert TrainingCheckpointer(str(tmp_path / "lose"),
                                    async_write=False).restore(restored)
        import jax

        for a, b in zip(jax.tree.leaves(src_net.params_),
                        jax.tree.leaves(restored.params_)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        commit = json.load(open(os.path.join(got["path"], "COMMIT")))
        assert commit["cloned_from"] == src_gen

    def test_corrupt_source_raises_typed_verify_error(self, tmp_path):
        _trained_lineage(str(tmp_path / "win"), steps=1)
        lineage = os.path.join(str(tmp_path / "win"), "latest")
        gen = lineage_state(str(tmp_path / "win"))["newest_committed"]
        faults._flip_bit_in_shard(os.path.join(lineage, gen))
        with pytest.raises(CheckpointVerifyError) as ei:
            clone_generation(os.path.join(lineage, gen),
                             str(tmp_path / "lose"))
        assert ei.value.reason
        assert not lineage_state(str(tmp_path / "lose"))["committed"]


class TestFleetClonePaths:
    def _two_trial_fleet(self, tmp_path, reg=None):
        fleet = _fleet(tmp_path, _score_runner(_lr_score), n_trials=4,
                       reg=reg, pbt_quantile=0.25)
        for tid in ("t00", "t01"):
            _trained_lineage(fleet.trials[tid].ckpt_dir, steps=2,
                             seed=5 if tid == "t00" else 7)
        return fleet

    def test_clone_into_slot_ok_perturbs_loser(self, tmp_path):
        reg = MetricsRegistry()
        fleet = self._two_trial_fleet(tmp_path, reg)
        try:
            loser, winner = fleet.trials["t01"], fleet.trials["t00"]
            before = dict(loser.hparams)
            outcome = fleet._clone_into_slot(loser, winner, rung=1)
        finally:
            fleet.close()
        assert outcome == "ok"
        assert loser.cloned_from.startswith("t00/")
        assert loser.hparams != before
        # perturbation stays inside the space bounds
        assert 1e-4 <= loser.hparams["lr"] <= 1e-1
        # shape-bearing int hyperparameters are inherited VERBATIM from the
        # winner: the cloned weights must still fit the net
        assert loser.hparams["hidden"] == winner.hparams["hidden"]
        # the loser's own stale lineage was retired: only the clone remains
        inv = lineage_state(loser.ckpt_dir)
        assert [g["generation"] for g in inv["committed"]] \
            == [loser.cloned_from.split("/", 1)[1]]
        series = reg.snapshot()["tdl_trial_clones_total"]["series"]
        assert {(s["labels"]["outcome"], s["value"])
                for s in series} == {("ok", 1.0)}
        clones = [r for r in fleet.state["journal"] if r["kind"] == "clone"]
        assert clones[0]["outcome"] == "ok"
        assert clones[0]["new_hparams"] == {
            k: v for k, v in loser.hparams.items() if k != "__id__"}

    def test_perturbation_is_resume_deterministic(self, tmp_path):
        fleet = self._two_trial_fleet(tmp_path)
        try:
            winner = fleet.trials["t00"]
            a = fleet._perturb(winner.hparams, fleet._rs("pbt", 1, "t01"))
            b = fleet._perturb(winner.hparams, fleet._rs("pbt", 1, "t01"))
            spread = {json.dumps(
                fleet._perturb(winner.hparams, fleet._rs("pbt", r, "t01")),
                sort_keys=True) for r in range(16)}
        finally:
            fleet.close()
        assert a == b  # same (seed, rung, loser) → identical explore
        assert len(spread) > 1  # different rungs do explore differently
        assert all(json.loads(s)["hidden"] == winner.hparams["hidden"]
                   for s in spread)

    def test_corrupt_newest_falls_back_to_older_generation(self, tmp_path):
        reg = MetricsRegistry()
        fleet = self._two_trial_fleet(tmp_path, reg)
        try:
            winner = fleet.trials["t00"]
            lineage = os.path.join(winner.ckpt_dir, "latest")
            newest = lineage_state(winner.ckpt_dir)["newest_committed"]
            faults._flip_bit_in_shard(os.path.join(lineage, newest))
            outcome = fleet._clone_into_slot(fleet.trials["t01"], winner, 1)
        finally:
            fleet.close()
        assert outcome == "fallback"
        # the corrupt source is quarantined as evidence, off the clone path
        inv = lineage_state(winner.ckpt_dir)
        assert newest not in [g["generation"] for g in inv["committed"]]
        assert inv["quarantined"]
        # loser actually received the older generation
        loser_inv = lineage_state(fleet.trials["t01"].ckpt_dir)
        assert loser_inv["newest_committed"]
        ev = [e for e in _fleet_events(fleet) if e["kind"] == "trial_clone"]
        assert ev and ev[0]["outcome"] == "fallback" and ev[0]["quarantined"]
        series = reg.snapshot()["tdl_trial_clones_total"]["series"]
        assert {(s["labels"]["outcome"], s["value"])
                for s in series} == {("fallback", 1.0)}
        # winner itself survives: one bad generation is not a bad trial
        assert winner.status != "quarantined"

    def test_fully_corrupt_winner_is_quarantined_loser_keeps_weights(
            self, tmp_path):
        reg = MetricsRegistry()
        fleet = self._two_trial_fleet(tmp_path, reg)
        try:
            winner = fleet.trials["t00"]
            lineage = os.path.join(winner.ckpt_dir, "latest")
            for g in lineage_state(winner.ckpt_dir)["committed"]:
                faults._flip_bit_in_shard(os.path.join(lineage,
                                                       g["generation"]))
            loser = fleet.trials["t01"]
            before_inv = lineage_state(loser.ckpt_dir)["newest_committed"]
            before_hp = dict(loser.hparams)
            outcome = fleet._clone_into_slot(loser, winner, 1)
        finally:
            fleet.close()
        assert outcome == "failed"
        assert winner.status == "quarantined"
        assert winner.quarantine_reason == "clone_source"
        # the loser is untouched: same weights, same hyperparameters
        assert lineage_state(loser.ckpt_dir)["newest_committed"] == before_inv
        assert loser.hparams == before_hp
        series = reg.snapshot()["tdl_trial_clones_total"]["series"]
        assert {(s["labels"]["outcome"], s["value"])
                for s in series} == {("failed", 1.0)}
        reasons = reg.snapshot()["tdl_trial_quarantined_total"]["series"]
        assert {s["labels"]["reason"] for s in reasons} == {"clone_source"}

    def test_injected_corrupt_clone_fault_is_one_shot(self, tmp_path,
                                                      monkeypatch):
        """The chaos clause: ``corrupt_clone`` bit-flips the FIRST clone
        source read, the fallback read sees healthy bytes — recovery is
        provable."""
        monkeypatch.setenv(faults.ENV_SPEC, "corrupt_clone")
        fleet = self._two_trial_fleet(tmp_path)
        try:
            outcome = fleet._clone_into_slot(fleet.trials["t01"],
                                             fleet.trials["t00"], 0)
        finally:
            fleet.close()
        assert outcome == "fallback"  # corrupted once, older gen healthy


# ------------------------------------------------------------- spool reader


class TestSpooledScores:
    def _spool(self, d, proc, wall, trial, score, iteration):
        payload = {"proc": proc, "wall": wall, "snapshot": {
            "tdl_trial_score": {"type": "gauge", "series": [
                {"labels": {"trial": trial}, "value": score}]},
            "tdl_trial_iteration": {"type": "gauge", "series": [
                {"labels": {"trial": trial}, "value": iteration}]}}}
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"tdl_metrics_{proc}.1.json"), "w") as f:
            json.dump(payload, f)

    def test_newest_iteration_wins_across_procs(self, tmp_path):
        d = str(tmp_path)
        self._spool(d, "t00-rank0", 1.0, "t00", 0.5, 4)
        self._spool(d, "t01-rank0", 2.0, "t01", 0.7, 8)
        got = spooled_scores(d, registry=MetricsRegistry())
        assert got == {"t00": (4, 0.5), "t01": (8, 0.7)}

    def test_torn_spool_degrades_not_raises(self, tmp_path):
        d = str(tmp_path)
        self._spool(d, "t00-rank0", 1.0, "t00", 0.5, 4)
        with open(os.path.join(d, "tdl_metrics_t01-rank0.1.json"), "w") as f:
            f.write('{"torn')
        reg = MetricsRegistry()
        assert spooled_scores(d, registry=reg) == {"t00": (4, 0.5)}
        errs = reg.snapshot()["tdl_spool_read_errors_total"]["series"]
        assert sum(s["value"] for s in errs) == 1.0


# ------------------------------------------------------------------ metrics


class TestTrialMetrics:
    def test_state_gauge_is_exclusive_per_trial(self):
        reg = MetricsRegistry()
        m = trial_metrics(reg)
        set_trial_state(m, "t00", "running")
        set_trial_state(m, "t00", "quarantined")
        set_trial_state(m, "t01", "running")
        series = {(s["labels"]["trial"], s["labels"]["state"]): s["value"]
                  for s in reg.snapshot()["tdl_trial_state"]["series"]}
        assert series[("t00", "quarantined")] == 1.0
        assert series[("t00", "running")] == 0.0
        assert series[("t01", "running")] == 1.0
        assert sum(v for (t, _), v in series.items() if t == "t00") == 1.0

    def test_unknown_state_is_a_bug_not_a_label(self):
        m = trial_metrics(MetricsRegistry())
        with pytest.raises(ValueError):
            set_trial_state(m, "t00", "confused")

    def test_all_families_declared(self):
        reg = MetricsRegistry()
        trial_metrics(reg)
        snap = reg.snapshot()
        assert {"tdl_trial_state", "tdl_trial_rung_promotions_total",
                "tdl_trial_quarantined_total", "tdl_trial_clones_total",
                "tdl_fleet_disk_bytes", "tdl_trial_score",
                "tdl_trial_iteration"} <= set(snap)


# ---------------------------------------- trial-terminal decision AST lint


_DECISION_EVENTS = {
    "_quarantine_trial": "trial_quarantine",
    "_demote_trial": "trial_demote",
    "_clone_into_slot": "trial_clone",
    "_promote_winner": "trial_promote",
}


def _record_literals(node):
    """Every ``*.record("<literal>", ...)`` / ``*._record("<literal>", ...)``
    call under ``node``: (kind, lineno)."""
    out = []
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("record", "_record")
                and sub.args
                and isinstance(sub.args[0], ast.Constant)
                and isinstance(sub.args[0].value, str)):
            out.append((sub.args[0].value, sub.lineno))
    return out


def _unflighted_decision_paths(tree):
    """Offenders: a trial-terminal decision method that never records its
    flight kind, or that can RETURN before the first record (a delegated
    ``return self._other_decision(...)`` is exempt — the callee records)."""
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef) \
                or node.name not in _DECISION_EVENTS:
            continue
        kind = _DECISION_EVENTS[node.name]
        recs = [ln for k, ln in _record_literals(node) if k == kind]

        def _delegated(ret):
            v = ret.value
            return (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr in _DECISION_EVENTS)

        returns = [sub for sub in ast.walk(node)
                   if isinstance(sub, ast.Return)]
        if not recs:
            # a method that only ever delegates to another decision method
            # is audited by the callee
            if not (returns and all(map(_delegated, returns))):
                offenders.append(f"{node.name}: never records {kind!r}")
            continue
        first = min(recs)
        for sub in returns:
            if sub.lineno >= first or _delegated(sub):
                continue
            offenders.append(
                f"{node.name}:{sub.lineno} returns before recording {kind!r}")
    return offenders


def test_every_trial_terminal_decision_records_a_flight_event():
    src = (ROOT / "deeplearning4j_tpu" / "arbiter" / "fleet.py").read_text()
    tree = ast.parse(src, filename="arbiter/fleet.py")
    found = {node.name for node in ast.walk(tree)
             if isinstance(node, ast.FunctionDef)}
    missing = set(_DECISION_EVENTS) - found
    assert not missing, f"decision methods renamed/removed: {missing}"
    offenders = _unflighted_decision_paths(tree)
    assert not offenders, (
        "trial-terminal decision paths without a flight event "
        f"(the sweep audit trail would silently lose verdicts): {offenders}")


def test_decision_lint_catches_planted_offenders():
    planted = ast.parse(textwrap.dedent("""
        class F:
            def _quarantine_trial(self, slot, rung, reason):
                self.count += 1  # decided, never audited

            def _demote_trial(self, slot, rung, reason):
                if reason == "straggler":
                    return None  # early exit skips the audit
                self._record("trial_demote", trial=slot.trial_id)

            def _clone_into_slot(self, loser, winner, rung):
                self._record("trial_clone", outcome="ok")
                return "ok"

            def _promote_winner(self, slot, score):
                return self._quarantine_trial(slot, 0, "x")  # delegated: ok
    """))
    offenders = _unflighted_decision_paths(planted)
    assert len(offenders) == 2
    assert any("_quarantine_trial" in o for o in offenders)
    assert any("_demote_trial" in o for o in offenders)
    assert not any("_clone_into_slot" in o for o in offenders)
    assert not any("_promote_winner" in o for o in offenders)


def test_fleet_trial_kinds_are_registered_event_kinds():
    for kind in ("trial_spawn", "trial_score", "trial_rung_promote",
                 *_DECISION_EVENTS.values()):
        assert kind in flight.EVENT_KINDS


# ------------------------------------------------------------ slow: chaos


def _write_fleet_config(tmp_path, workdir, *, n_trials=6, rungs=(2, 4),
                        extra=None):
    cfg = {
        "workdir": workdir,
        "generator": "random",
        "seed": 7,
        "n_trials": n_trials,
        "rungs": list(rungs),
        "reduction": 2,
        "max_concurrent": 2,
        "rung_timeout_s": 240.0,
        "trial_max_restarts": 2,
        "backoff_base_s": 0.1,
        "backoff_max_s": 0.5,
        "hang_timeout": 20.0,
        "task": {"kind": "synth_classify", "seed": 11},
        "spaces": {
            "learning_rate": {"kind": "continuous", "lo": 1e-3, "hi": 1e-1,
                              "log_scale": True},
            "hidden": {"kind": "integer", "lo": 4, "hi": 32},
        },
    }
    cfg.update(extra or {})
    path = tmp_path / "fleet_config.json"
    path.write_text(json.dumps(cfg))
    return str(path)


@pytest.mark.slow
class TestFleetGangChaos:
    def test_gang_sweep_survives_crashes_and_corrupt_clone(
            self, tmp_path, monkeypatch):
        """The chaos acceptance core, sized for CI: a real-gang sweep where
        one trial's worker crashes (supervisor restarts it), another crashes
        EVERY incarnation (quarantined), and the fleet-side corrupt_clone
        fault bit-flips the first PBT clone source (fallback evidenced in
        flight events and the journal). The sweep still promotes a winner
        whose score is readable from the merged spool."""
        from deeplearning4j_tpu.arbiter.fleet import GangTrialRunner

        wd = str(tmp_path / "sweep")

        def fault_spec_for(slot):
            if slot.trial_id == "t01":
                return "crash@iteration=1,restart=0"  # once, then clean
            if slot.trial_id == "t03":
                return "crash@iteration=1,every=1"  # unrecoverable
            return ""

        monkeypatch.setenv(faults.ENV_SPEC, "corrupt_clone")
        gen = RandomSearchGenerator(
            {"learning_rate": ContinuousParameterSpace(1e-3, 1e-1,
                                                       log_scale=True),
             "hidden": IntegerParameterSpace(4, 32)}, seed=7)
        runner = GangTrialRunner(
            wd, {"kind": "synth_classify", "seed": 11},
            gang_max_restarts=2, hang_timeout=30.0,
            fault_spec_for=fault_spec_for)
        reg = MetricsRegistry()
        fleet = TrialFleet(gen, runner, workdir=wd, n_trials=6,
                           rungs=(2, 4), reduction=2, pbt=True,
                           pbt_quantile=0.34, seed=7, registry=reg,
                           rung_timeout_s=420.0, trial_max_restarts=1,
                           backoff_base_s=0.1, max_concurrent=2)
        try:
            winner = fleet.run()
        finally:
            fleet.close()
        assert winner["trial"] != "t03"
        # the always-crashing trial burned its budgets and was quarantined
        assert fleet.trials["t03"].status == "quarantined"
        # the restarted trial survived its single crash
        assert fleet.trials["t01"].status != "quarantined"
        # every trial's score is distinguishable in ONE merged scrape
        scores = spooled_scores(runner.spool_dir, registry=reg)
        scored_ids = {t.trial_id for t in fleet.trials.values()
                      if t.scores}
        assert scored_ids <= set(scores)
        # the corrupt_clone either hit a clone (fallback journaled) or no
        # clone happened this sweep — if one did, recovery must be evidenced
        clones = [r for r in fleet.state["journal"] if r["kind"] == "clone"]
        if clones:
            assert clones[0]["outcome"] in ("fallback", "ok")
        # disk bounded: demoted trials' lineages collapsed to one generation
        for t in fleet.trials.values():
            if t.status in ("demoted", "quarantined"):
                assert len(lineage_state(t.ckpt_dir)["committed"]) <= 1

    def test_sigkilled_fleet_cli_resumes_mid_rung(self, tmp_path):
        """SIGKILL the unattended fleet CLI mid-sweep; rerunning the same
        config resumes from the journal and finishes with a winner whose
        pre-kill journaled scores were not recomputed."""
        wd = str(tmp_path / "sweep")
        cfg = _write_fleet_config(tmp_path, wd, n_trials=4, rungs=(2, 4))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu.arbiter.fleet", cfg],
            env=env, cwd=str(ROOT), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        state_path = os.path.join(wd, "fleet_state.json")
        deadline = time.monotonic() + 300.0
        journaled = 0
        try:
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break  # finished before we could kill it — still fine
                try:
                    journaled = len(json.load(open(state_path))["journal"])
                except (OSError, ValueError, KeyError):
                    journaled = 0
                if journaled >= 2:  # mid-rung: some scores down, no winner
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                    break
                time.sleep(0.5)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        pre = json.load(open(state_path))
        pre_scores = {(r["trial"], r["rung"]): r["score"]
                      for r in pre["journal"] if r["kind"] == "score"}
        out = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.arbiter.fleet", cfg],
            env=env, cwd=str(ROOT), capture_output=True, text=True,
            timeout=540)
        assert out.returncode == 0, out.stdout + out.stderr
        winner = json.loads(out.stdout.strip().splitlines()[-1])
        post = json.load(open(state_path))
        assert post["winner"]["trial"] == winner["trial"]
        # pre-kill journaled scores survived verbatim (not recomputed)
        post_scores = {(r["trial"], r["rung"]): r["score"]
                       for r in post["journal"] if r["kind"] == "score"}
        for key, score in pre_scores.items():
            assert post_scores[key] == score
