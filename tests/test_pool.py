"""Elastic replica-pool serving (ISSUE 13 tentpole piece 3).

Covers: router dispatch + readiness aggregation (ready iff >= min_replicas
warm, /health live throughout — the satellite readiness fix), transparent
failover + respawn after a SIGKILL, the client's pool-unready retry contract
(503 treated like 429, distinct retry label, breaker untouched), the
autoscaler's act-don't-flap state machine, and the slow replica-kill +
10x-burst chaos acceptance.
"""

import json
import pathlib
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.monitoring.alerts import AlertEngine, AlertRule
from deeplearning4j_tpu.serving import (JsonModelClient, PoolAutoscaler,
                                        ServingPool)

_WORKERS = str(pathlib.Path(__file__).resolve().parent / "pool_workers.py")


def _pool(tmp_path, target="stub_server", **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("registry", MetricsRegistry())
    return ServingPool(f"{_WORKERS}:{target}", workdir=str(tmp_path / "pool"),
                       **kw)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _post(port, payload, headers=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _counter_values(reg, name):
    m = reg.get(name)
    if m is None:
        return {}
    return {tuple(s["labels"].values()): s["value"]
            for s in m.snapshot()["series"]}


def _kill_one_replica(pool):
    with pool._lock:
        handle = next(h for h in pool._replicas.values() if h.alive)
    handle.proc.kill()  # SIGKILL: no drain, no goodbye
    return handle.id


# --------------------------------------------------- readiness (satellite)


def test_pool_ready_flips_below_min_replicas_health_stays_live(tmp_path):
    """Satellite 1: /ready on the front door is the POOL's readiness — 503
    the moment fewer than min_replicas replicas are warm — while /health
    stays 200 through the whole replica restart."""
    reg = MetricsRegistry()
    pool = _pool(tmp_path, replicas=2, min_replicas=2, registry=reg).start()
    try:
        assert pool.wait_ready(60.0)
        assert _get(pool.port, "/ready")[0] == 200
        status, body, _ = _post(pool.port, [[1.0, 2.0, 3.0, 4.0]])
        assert status == 200
        np.testing.assert_allclose(body["output"], [[2.0, 4.0, 6.0, 8.0]])

        killed = _kill_one_replica(pool)
        # the monitor notices within a poll or two; /ready must flip 503
        saw_unready = False
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            assert _get(pool.port, "/health")[0] == 200  # ALWAYS live
            try:
                _get(pool.port, "/ready", timeout=5)
            except urllib.error.HTTPError as e:
                assert e.code == 503
                body = json.loads(e.read())
                assert "pool not ready" in body["error"]
                assert e.headers.get("Retry-After") is not None
                saw_unready = True
                break
            time.sleep(0.05)
        assert saw_unready, "pool /ready never flipped 503 after the kill"
        # the monitor respawns the dead replica; readiness recovers
        assert pool.wait_ready(60.0)
        assert _get(pool.port, "/ready")[0] == 200
        deaths = _counter_values(reg, "tdl_worker_deaths_total")
        assert deaths[("replica_crash",)] >= 1
        with pool._lock:
            assert pool._replicas[killed].restarts >= 1
        # pool gauges exist and agree
        assert reg.get("tdl_pool_size").value >= 2
        # the state gauge emits 0 for a replica's OTHER states (its help
        # text contract): {state="dead"} reads 0 when healthy, not missing
        states = {(s["labels"]["replica"], s["labels"]["state"]): s["value"]
                  for s in reg.get("tdl_pool_replica_state")
                  .snapshot()["series"]}
        ready_replica = next(r for (r, st), v in states.items()
                             if st == "ready" and v == 1.0)
        assert states[(ready_replica, "dead")] == 0.0
    finally:
        pool.stop()


def test_router_failover_hides_a_dead_replica(tmp_path):
    """A request hitting a just-killed replica fails over to a sibling
    transparently — the client sees 200, never a connection error."""
    pool = _pool(tmp_path, replicas=2, min_replicas=1).start()
    try:
        assert pool.wait_ready(60.0)
        # both replicas must be ready so the router will route to either
        deadline = time.monotonic() + 30.0
        while pool.ready_count < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.ready_count == 2
        _kill_one_replica(pool)
        # immediately: no monitor poll has necessarily run yet
        oks = 0
        for _ in range(8):
            status, body, _ = _post(pool.port, [[1.0, 1.0, 1.0, 1.0]])
            assert status == 200
            np.testing.assert_allclose(body["output"], [[2.0] * 4])
            oks += 1
        assert oks == 8
    finally:
        pool.stop()


def test_client_treats_pool_unready_like_429(tmp_path):
    """Satellite 6: a router 503 (pool not ready) is retried honoring
    Retry-After, counted under tdl_client_retries_total{reason=
    "pool_unready"}, and never marches the circuit breaker toward open."""
    reg = MetricsRegistry()
    pool = _pool(tmp_path, replicas=1, min_replicas=1,
                 extra_env={"TDL_STUB_START_DELAY": "2.0"}).start()
    try:
        # the lone replica sleeps 2s before serving: the pool answers 503
        # "pool not ready" meanwhile — a rolling-restart window in miniature
        client = JsonModelClient(port=pool.port, retries=30,
                                 backoff_base=0.05, backoff_max=0.3,
                                 breaker_threshold=2,  # would trip on TWO
                                 registry=reg)
        out = client.predict([[3.0, 0.0, 0.0, 0.0]])
        np.testing.assert_allclose(out, [[6.0, 0.0, 0.0, 0.0]])
        retries = _counter_values(reg, "tdl_client_retries_total")
        assert retries[("pool_unready",)] >= 1
        # the breaker never opened despite >= breaker_threshold 503s: the
        # next call goes straight through
        assert client._consecutive_failures == 0
        np.testing.assert_allclose(client.predict([[1.0, 0, 0, 0]]),
                                   [[2.0, 0, 0, 0]])
    finally:
        pool.stop()


def test_respawn_heartbeat_is_per_incarnation(tmp_path):
    """A respawned replica must NOT inherit the dead incarnation's heartbeat
    file: consuming the stale beat would downgrade the new process's startup
    budget from startup_grace to hang_timeout and kill any replica that
    spends longer than that importing jax + building its model."""
    from deeplearning4j_tpu.monitoring.heartbeat import (ENV_DIR,
                                                         HeartbeatWriter)

    pool = _pool(tmp_path, replicas=1)
    try:
        with pool._lock:
            h = pool._spawn_replica()
        assert h.hb_dir.endswith("i0")
        assert pool._child_env(h)[ENV_DIR] == h.hb_dir
        # incarnation 0 beats, then dies; the pool respawns in place
        HeartbeatWriter(h.hb_dir, h.id, 0.0).beat(7)
        h.proc.kill()
        h.proc.wait(10)
        h.restarts += 1
        with pool._lock:
            pool._spawn_replica(h)
        assert h.hb_dir.endswith("i1")
        assert pool._child_env(h)[ENV_DIR] == h.hb_dir
        # the stale i0 beat is INVISIBLE to incarnation 1's staleness check:
        # last_hb stays None, so the budget stays startup_grace
        pool._check_heartbeat(h, time.monotonic())
        assert h.last_hb is None
        # a beat in the incarnation's own dir IS seen
        HeartbeatWriter(h.hb_dir, h.id, 0.0).beat(1)
        pool._check_heartbeat(h, time.monotonic())
        assert h.last_hb is not None
    finally:
        with pool._lock:
            handles = list(pool._replicas.values())
        for hh in handles:
            if hh.alive:
                hh.proc.kill()
                hh.proc.wait(10)


def test_exhausted_restart_budget_frees_the_seat(tmp_path):
    """A replica out of restart budget is RETIRED, not left dead in the
    serving set: the poll loop reaps it so _reconcile can backfill a fresh
    replica — a transient failure burst can never permanently pin the pool
    below min_replicas."""
    from deeplearning4j_tpu.serving.pool import ReplicaHandle

    pool = _pool(tmp_path, replicas=1, max_restarts_per_replica=0)
    h = ReplicaHandle(id=0)
    pool._replicas[0] = h
    pool._on_death(h, "replica_crash", time.monotonic())
    assert h.state == "dead" and h.retiring
    pool._poll_replicas()  # dead + retiring => reaped
    assert 0 not in pool._replicas
    with pool._lock:  # the seat is free for _reconcile to backfill
        assert not [x for x in pool._replicas.values() if not x.retiring]


class _FakeProc:
    """poll()-able stand-in so a ReplicaHandle counts as alive without a
    real subprocess."""

    pid = 0

    def __init__(self):
        self._dead = False

    def poll(self):
        return 0 if self._dead else None

    def send_signal(self, sig):
        self._dead = True

    def kill(self):
        self._dead = True

    def wait(self, timeout=None):
        return 0


def _stub_replica_http(code, body):
    """In-thread HTTP stub answering every POST with one canned response."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            payload = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_pool_restart_after_stop_is_clean(tmp_path):
    """start() after stop() spawns a FRESH replica set: stale dead handles
    must not be death-counted, respawned, and re-retired on top of it."""
    reg = MetricsRegistry()
    pool = _pool(tmp_path, replicas=1, registry=reg)
    pool.start()
    try:
        assert pool.wait_ready(60.0)
        pool.stop()
        assert not pool._replicas
        pool.start()
        assert pool.wait_ready(60.0)
        deaths = _counter_values(reg, "tdl_worker_deaths_total")
        assert deaths.get(("replica_crash",), 0) == 0
        with pool._lock:
            assert len(pool._replicas) == 1
    finally:
        pool.stop()


def test_router_fails_over_on_replica_503(tmp_path):
    """A replica 503 (draining/warming: the request was NOT processed) must
    fail over to a sibling like a connection error — returning the
    replica's own 503 (no "pool not ready" marker) would march the client
    breaker during a rolling restart a sibling could have absorbed."""
    from deeplearning4j_tpu.serving.pool import ReplicaHandle

    draining = _stub_replica_http(503, {"error": "server shutting down"})
    serving = _stub_replica_http(200, {"output": [[2.0]]})
    pool = _pool(tmp_path, replicas=2)
    try:
        with pool._lock:
            pool._replicas[0] = ReplicaHandle(
                id=0, proc=_FakeProc(), port=draining.server_address[1],
                state="ready")
            pool._replicas[1] = ReplicaHandle(
                id=1, proc=_FakeProc(), port=serving.server_address[1],
                state="ready")
        pool._start_router()
        # least-loaded tie breaks to id 0 (the draining one) first
        status, body, headers = _post(pool.port, [[1.0]])
        assert status == 200 and headers["X-Replica"] == "1"
        assert body["output"] == [[2.0]]
        h0 = pool._replicas[0]
        assert h0.state == "unready"  # stop routing to it until a probe
        assert h0.fails == 0          # but NOT a breaker signal
    finally:
        pool.stop(drain=False)
        draining.shutdown()
        serving.shutdown()


def test_router_forward_timeout_covers_the_deadline(tmp_path):
    """The per-request forward timeout must exceed both the replica's 30s
    default deadline and an explicit X-Deadline-Ms (plus margin): a slow
    but within-deadline generation misclassified as a connection failure
    would be breaker-counted and re-dispatched in duplicate."""
    pool = _pool(tmp_path)
    assert pool._forward_timeout({}) == 40.0
    assert pool._forward_timeout({"X-Deadline-Ms": "2000"}) == 40.0
    assert pool._forward_timeout({"X-Deadline-Ms": "60000"}) == 65.0
    assert pool._forward_timeout({"X-Deadline-Ms": "nope"}) == 40.0


def test_child_env_identity_keys_resist_parent_pollution(tmp_path, monkeypatch):
    """Per-replica identity keys are pool-owned: a pool launched inside an
    already-supervised process (TDL_PROC_NAME / TDL_HEARTBEAT_DIR set in
    the parent env) must not leak the parent's identity into replicas —
    that would merge every replica's metrics under one proc and point
    heartbeats where the monitor never looks."""
    from deeplearning4j_tpu.monitoring.flight import ENV_PROC
    from deeplearning4j_tpu.monitoring.heartbeat import (ENV_DIR,
                                                         ENV_INTERVAL)
    from deeplearning4j_tpu.serving.pool import ReplicaHandle

    monkeypatch.setenv(ENV_PROC, "rank0")
    monkeypatch.setenv(ENV_DIR, "/somewhere/else")
    monkeypatch.setenv(ENV_INTERVAL, "60.0")
    pool = _pool(tmp_path, heartbeat_interval=0.25)
    h = ReplicaHandle(id=3, hb_dir=str(tmp_path / "pool" / "hb" / "i0"))
    env = pool._child_env(h)
    assert env[ENV_PROC] == "replica3"
    assert env[ENV_DIR] == h.hb_dir
    assert env[ENV_INTERVAL] == "0.25"


def test_router_error_paths_deliver_json(tmp_path):
    """Router early 4xxs mirror the replica server's contract: the unread
    body is drained so the error JSON arrives (no RST mid-upload), and a
    malformed Content-Length is a 400 naming the bad value — not a 413
    claiming the header is missing."""
    import http.client

    pool = _pool(tmp_path, replicas=1, max_body_bytes=1024).start()
    try:
        assert pool.wait_ready(60.0)
        big = [[1.0] * 200_000]  # ~1MB encoded: past any socket buffer
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(pool.port, big)
        assert ei.value.code == 413
        assert "exceeds" in json.loads(ei.value.read())["error"]
        # unknown endpoint with a body pending: drained, 404 delivered
        req = urllib.request.Request(
            f"http://127.0.0.1:{pool.port}/nope", data=b"x" * 512,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 404
        conn = http.client.HTTPConnection("127.0.0.1", pool.port, timeout=10)
        try:
            conn.putrequest("POST", "/predict")
            conn.putheader("Content-Length", "abc")
            conn.putheader("Content-Type", "application/json")
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 400
            assert b"bad Content-Length" in resp.read()
        finally:
            conn.close()
    finally:
        pool.stop()


# -------------------------------------------------------------- autoscaler


class _FakeEngine:
    """Engine stand-in: evaluate() reports whatever the test scripted."""

    def __init__(self):
        self.firing = set()
        self.rules = (AlertRule("queue_hot", "tdl_inference_queue_depth",
                                ">=", 1),)

    def evaluate(self):
        return [{"rule": "queue_hot", "firing": "queue_hot" in self.firing}]


def test_autoscaler_scales_up_down_without_flapping(tmp_path):
    reg = MetricsRegistry()
    pool = _pool(tmp_path, replicas=2, min_replicas=1, max_replicas=4,
                 registry=reg)  # never started: scale_to needs no processes
    engine = _FakeEngine()
    scaler = PoolAutoscaler(pool, engine, scale_up_rules=("queue_hot",),
                            cooldown_s=0.2, scale_down_idle_evals=3)
    engine.firing = {"queue_hot"}
    assert scaler.tick() == "up" and pool.desired == 3
    # cooldown: an immediately-following firing tick does NOT scale again
    assert scaler.tick() is None and pool.desired == 3
    time.sleep(0.25)
    assert scaler.tick() == "up" and pool.desired == 4
    time.sleep(0.25)
    assert scaler.tick() is None and pool.desired == 4  # at max_replicas
    # clearing: needs scale_down_idle_evals consecutive all-clear ticks
    engine.firing = set()
    time.sleep(0.25)
    assert scaler.tick() is None
    assert scaler.tick() is None
    assert scaler.tick() == "down" and pool.desired == 3
    # streak resets after an action: not an immediate cascade to min
    assert scaler.tick() is None
    events = _counter_values(reg, "tdl_pool_scale_events_total")
    assert events[("up",)] == 2 and events[("down",)] == 1
    assert [a["action"] for a in scaler.actions] == ["up", "up", "down"]
    assert scaler.actions[0]["rules"] == ["queue_hot"]


def test_autoscaler_rejects_unknown_rules():
    engine = _FakeEngine()
    with pytest.raises(ValueError, match="nonexistent_rule"):
        PoolAutoscaler(object(), engine, scale_up_rules=("nonexistent_rule",))


def test_scale_to_clamps_and_counts(tmp_path):
    reg = MetricsRegistry()
    pool = _pool(tmp_path, replicas=2, min_replicas=1, max_replicas=3,
                 registry=reg)
    assert pool.scale_to(99) == 3
    assert pool.scale_to(0) == 1
    assert pool.scale_to(1) == 1  # no-op: no event counted
    events = _counter_values(reg, "tdl_pool_scale_events_total")
    assert events[("up",)] == 1 and events[("down",)] == 1


# ---------------------------------------------------- chaos (slow tier)


# ---------------------------------------- zero-downtime model swap (ISSUE 14)


def _versions(tmp_path):
    v2 = tmp_path / "v2.json"
    v2.write_text(json.dumps({"scale": 2}))
    v3 = tmp_path / "v3.json"
    v3.write_text(json.dumps({"scale": 3}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"fail": True}))
    return str(v2), str(v3), str(bad)


def test_swap_model_rolls_replicas_zero_downtime(tmp_path):
    """swap_model rolls every replica onto the new checkpoint surge-first:
    ready never dips below the desired count, every post-swap response is
    the new model's, later scale-ups inherit the new version, and the swap
    is counted."""
    _, v3, _ = _versions(tmp_path)
    reg = MetricsRegistry()
    pool = _pool(tmp_path, target="swappable_server", replicas=2,
                 min_replicas=2, registry=reg).start()
    try:
        assert pool.wait_ready(60.0)
        assert _post(pool.port, [[1.0, 1.0, 1.0, 1.0]])[1]["output"][0][0] == 2.0
        old_ids = set(pool.replica_states())

        res = pool.swap_model(v3)
        assert res["ok"] and res["swapped"] == 2 and not res["rolled_back"]
        assert pool.ready_count >= 2  # never below desired, let alone min
        assert set(pool.replica_states()).isdisjoint(old_ids)
        for _ in range(4):
            assert _post(pool.port, [[1.0, 1.0, 1.0, 1.0]])[1]["output"][0][0] == 3.0
        for row in pool.describe()["replicas"]:
            assert row["model"] == v3

        # a post-swap scale-up spawns the NEW version (default overrides)
        pool.scale_to(3)
        deadline = time.monotonic() + 60.0
        while pool.ready_count < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.ready_count == 3
        assert all(row["model"] == v3
                   for row in pool.describe()["replicas"])
        assert _counter_values(reg, "tdl_pool_swap_events_total") == {(): 1}
        assert _counter_values(reg, "tdl_pool_swap_rollbacks_total") == {}
    finally:
        pool.stop()


def test_swap_validation_failure_rolls_back(tmp_path):
    """A new version that cannot become ready is killed BEFORE any old
    replica is touched: the swap reports rollback, the rollback counter
    moves, and the old version keeps serving at full strength."""
    _, _, bad = _versions(tmp_path)
    reg = MetricsRegistry()
    pool = _pool(tmp_path, target="swappable_server", replicas=2,
                 min_replicas=2, registry=reg).start()
    try:
        assert pool.wait_ready(60.0)
        old_ids = set(pool.replica_states())
        res = pool.swap_model(bad, ready_timeout=12.0)
        assert not res["ok"] and res["rolled_back"] and res["swapped"] == 0
        assert set(pool.replica_states()) == old_ids  # old fleet untouched
        assert pool.ready_count >= 2
        assert _post(pool.port, [[1.0, 1.0, 1.0, 1.0]])[1]["output"][0][0] == 2.0
        assert _counter_values(reg, "tdl_pool_swap_rollbacks_total") == {(): 1}
        assert _counter_values(reg, "tdl_pool_swap_events_total") == {}
    finally:
        pool.stop()


def test_swap_model_preflight_rejects_corrupt_checkpoint(tmp_path):
    """ISSUE 15: a checkpoint whose lineage fails verification is rejected
    BEFORE any surge replica is spawned — the old fleet is untouched, the
    rollback metrics stay clean, and the distinct rejected counter moves.
    (A rollback means a surge replica ran against a bad version; pre-flight
    makes a torn/bit-flipped artifact never get that far.)"""
    import numpy as np

    from deeplearning4j_tpu.serde.checkpoint import (_array_crc, _gen_name,
                                                     _self_checksummed)

    # hand-roll a COMMITTED generation, then flip a byte in its shard
    ckroot = tmp_path / "ck"
    lineage = ckroot / "latest"
    gen = _gen_name(3)
    gendir = lineage / gen
    gendir.mkdir(parents=True)
    blob = {"__save_id__": np.asarray(3, np.int64),
            "params/0/W|0": np.arange(64, dtype=np.float32),
            "params/0/W|0|idx": np.asarray([[0, 64]], np.int64),
            "params/0/W|0|shape": np.asarray([64], np.int64)}
    with open(gendir / "shard_0.npz", "wb") as f:
        np.savez(f, **blob)
    manifest = _self_checksummed({
        "save_id": 3, "proc": 0, "shard": "shard_0.npz",
        "process_count": 1, "layout": None,
        "entries": {k: _array_crc(v) for k, v in blob.items()},
        "nbytes": 0})
    (gendir / "manifest_0.json").write_text(json.dumps(manifest))
    (gendir / "train_state.json").write_text(json.dumps(_self_checksummed(
        {"iteration": 3, "epoch": 0, "score": None, "process_count": 1,
         "generation": gen})))
    (gendir / "COMMIT").write_text("{}")
    (lineage / "LATEST").write_text(gen + "\n")
    # flip a byte INSIDE the weight array's payload (npz members are stored
    # uncompressed, so the raw bytes are findable) — latent bit-rot the
    # manifest CRCs must catch
    shard = gendir / "shard_0.npz"
    raw = shard.read_bytes()
    off = raw.index(blob["params/0/W|0"].tobytes()) + 8
    with open(shard, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))

    reg = MetricsRegistry()
    pool = _pool(tmp_path, target="swappable_server", replicas=2,
                 min_replicas=2, registry=reg)  # deliberately NOT started
    with pytest.raises(ValueError, match="rejected checkpoint"):
        pool.swap_model(str(ckroot))
    # rejected at pre-flight: no surge replica was ever spawned, and the
    # rollback path (which implies a spawned surge) never engaged
    assert pool.replica_states() == {}
    assert _counter_values(reg, "tdl_pool_swap_rejected_total") == {(): 1}
    assert _counter_values(reg, "tdl_pool_swap_rollbacks_total") == {}
    assert _counter_values(reg, "tdl_pool_swap_events_total") == {}
    # the same pool object happily pre-flights a HEALTHY lineage: fix the
    # shard back and the verification gate opens (the roll itself would
    # then need a started pool — not exercised here)
    with open(shard, "r+b") as f:
        f.seek(off)
        f.write(bytes([b[0]]))
    from deeplearning4j_tpu.serde.checkpoint import verify_checkpoint

    assert verify_checkpoint(str(ckroot))["ok"]


def test_scale_down_drains_before_signal(tmp_path):
    """ISSUE 14 satellite (the drain fix): on scale-down the ROUTER stops
    dispatching first — the replica enters the explicit `draining` state —
    and the supervisor only signals it once its in-flight count hits zero,
    so no request can race into a dying replica and burn a breaker count."""
    pool = _pool(tmp_path, target="stub_server", replicas=2,
                 min_replicas=1).start()
    try:
        assert pool.wait_ready(60.0)
        with pool._lock:
            victim = max(pool._replicas.values(), key=lambda h: h.id)
            victim.inflight = 1  # pin an in-flight request on the victim
        pool.scale_to(1)
        deadline = time.monotonic() + 5.0
        while victim.state != "draining" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert victim.state == "draining" and victim.retiring
        assert pool.replica_states()[victim.id] == "draining"
        time.sleep(0.6)  # several monitor iterations
        # drained-but-busy: router excludes it, supervisor has NOT signaled
        assert victim.alive and not victim.signaled
        # _pick_replica counts the pick in-flight (it is the dispatch path,
        # not a query) — undo it so the probe doesn't pin the survivor
        picked = pool._pick_replica(set())
        assert picked is not victim
        if picked is not None:
            with pool._lock:
                picked.inflight -= 1
        with pool._lock:
            victim.inflight = 0  # the in-flight request completes
        deadline = time.monotonic() + 15.0
        while victim.id in pool.replica_states() and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.signaled
        assert victim.id not in pool.replica_states()
        assert not victim.alive
    finally:
        pool.stop()


@pytest.mark.slow
def test_pool_chaos_swap_under_load(tmp_path):
    """ISSUE 14 acceptance: a mid-traffic swap_model under the loadgen
    replay finishes with ONLY 200/429 escaping (zero 5xx/connection
    outcomes), /ready answering 200 throughout, the pool never below
    min_replicas ready — and after the window every response is the new
    model's."""
    from deeplearning4j_tpu.serving import LoadGenerator, TraceSpec

    _, v3, _ = _versions(tmp_path)
    reg = MetricsRegistry()
    pool = _pool(tmp_path, target="swappable_server", replicas=3,
                 min_replicas=2, registry=reg).start()
    try:
        assert pool.wait_ready(60.0)
        ready_codes = []
        min_ready = [99]
        stop = threading.Event()

        def ready_poller():
            while not stop.is_set():
                try:
                    status, _, _ = _get(pool.port, "/ready", timeout=5)
                except urllib.error.HTTPError as e:
                    status = e.code
                ready_codes.append(status)
                min_ready[0] = min(min_ready[0], pool.ready_count)
                time.sleep(0.05)

        poller = threading.Thread(target=ready_poller, daemon=True)
        poller.start()
        spec = TraceSpec(duration_s=8.0, base_rate=30.0, seed=3,
                         diurnal_amplitude=0.3)
        gen = LoadGenerator(spec, pool.port, n_clients=8,
                            payload=[[1.0, 2.0, 3.0, 4.0]])
        swap_result = {}

        def swap_mid_replay():
            time.sleep(1.5)  # let the replay reach steady state first
            swap_result.update(pool.swap_model(v3))

        swapper = threading.Thread(target=swap_mid_replay, daemon=True)
        swapper.start()
        report = gen.run()
        swapper.join(120.0)
        assert not swapper.is_alive()
        stop.set()
        poller.join(10.0)

        assert swap_result.get("ok"), swap_result
        assert swap_result["swapped"] == 3
        # 0 non-2xx beyond the usual 429 budget — no 5xx, no connection
        # errors, no pool-unready 503s leaked mid-roll
        assert set(report["outcomes"]) <= {"200", "429"}, report["outcomes"]
        assert report["outcomes"].get("200", 0) > 0
        # /ready stayed 200 for every poll across the whole swap window
        assert ready_codes and set(ready_codes) == {200}
        assert min_ready[0] >= 2  # never below min_replicas ready
        assert _post(pool.port, [[1.0, 1.0, 1.0, 1.0]])[1]["output"][0][0] == 3.0
        assert _counter_values(reg, "tdl_pool_swap_events_total") == {(): 1}
    finally:
        pool.stop()


@pytest.mark.slow
def test_pool_chaos_replica_kill_and_10x_burst(tmp_path):
    """ISSUE 13 acceptance (ISSUE 17 trace): 32 clients replaying a
    SHARED-PREFIX generative mix (N tenants x a common system prompt, the
    TraceSpec prefix mix that exercises CoW sharing on a paged session)
    with a 10x burst while a replica is SIGKILLed mid-flight — only
    200/429/504 ever escape (the router's failover + the client's
    pool_unready retry absorb the restart window), p99 stays bounded, and
    the pool size FOLLOWS the alert signal: up during the burst, back down
    after, with the alert interval paired (fired AND cleared) and no
    flap."""
    from deeplearning4j_tpu.serving import TraceSpec

    prompt_fn = TraceSpec(duration_s=1.0, base_rate=1.0, seed=7,
                          prefix_tenants=4, prefix_len=24, suffix_len=4,
                          prompt_vocab=256).prompt_fn()
    reg = MetricsRegistry()
    pool = _pool(
        tmp_path, target="generative_stub_server",
        replicas=2, min_replicas=1, max_replicas=4, registry=reg,
        extra_env={"TDL_STUB_STEP_DELAY": "0.004", "TDL_STUB_MAX_NEW": "8",
                   "TDL_STUB_QUEUE": "16"},
        heartbeat_interval=0.1).start()
    engine = AlertEngine(
        (AlertRule("inference_queue_depth_hwm", "tdl_inference_queue_depth",
                   ">=", 6, for_duration=2, clear_hysteresis=3,
                   description="pool admission queues filling"),),
        registry=MetricsRegistry(), spool_dir=pool.spool_dir)
    scaler = PoolAutoscaler(pool, engine,
                            scale_up_rules=("inference_queue_depth_hwm",),
                            cooldown_s=1.0, scale_down_idle_evals=6)
    try:
        assert pool.wait_ready(60.0)
        scaler.start(interval=0.25)

        outcomes = []
        latencies = []
        lock = threading.Lock()
        stop_burst = threading.Event()

        def client_worker(idx, requests, deadline_ms):
            client = JsonModelClient(port=pool.port, timeout=20, retries=10,
                                     backoff_base=0.02, backoff_max=0.2,
                                     breaker_threshold=10 ** 6)
            for r in range(requests):
                t0 = time.perf_counter()
                try:
                    client.predict(prompt_fn(idx * 100 + r),
                                   deadline_ms=deadline_ms,
                                   request_id=f"chaos-{idx}-{r}")
                    out = "200"
                except RuntimeError as e:
                    msg = str(e)
                    out = next((c for c in ("429", "504", "503", "500", "400")
                                if f"HTTP {c}" in msg), "error")
                with lock:
                    outcomes.append(out)
                    latencies.append(time.perf_counter() - t0)

        # phase 1: steady trickle (8 clients)
        steady = [threading.Thread(target=client_worker, args=(i, 6, 10_000))
                  for i in range(8)]
        for t in steady:
            t.start()
        time.sleep(1.0)
        # phase 2: the 10x burst (32 clients) + SIGKILL one replica mid-burst
        burst = [threading.Thread(target=client_worker, args=(100 + i, 8, 8_000))
                 for i in range(32)]
        for t in burst:
            t.start()
        time.sleep(0.5)
        _kill_one_replica(pool)
        for t in steady + burst:
            t.join(120.0)
        assert not any(t.is_alive() for t in steady + burst)
        stop_burst.set()
        # phase 3: recovery — let the alert clear and the scaler back off
        deadline = time.monotonic() + 20.0
        peak_desired = pool.desired
        while time.monotonic() < deadline and pool.desired > 2:
            time.sleep(0.25)

        with lock:
            outs = set(outcomes)
            lat = sorted(latencies)
        # ONLY 200/429/504 escape (503s are retried client-side as
        # pool_unready; connection errors are hidden by router failover)
        assert outs <= {"200", "429", "504"}, f"unexpected outcomes: {outs}"
        assert outcomes.count("200") > 0
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        assert p99 < 15.0  # bounded while the replacement warms
        # the pool FOLLOWED the alert: scaled up under the burst...
        events = _counter_values(reg, "tdl_pool_scale_events_total")
        assert events.get(("up",), 0) >= 1, f"no scale-up: {events}"
        assert peak_desired >= 3
        # ...and back down after, without flapping
        assert events.get(("down",), 0) >= 1, f"no scale-down: {events}"
        assert sum(events.values()) <= 6, f"autoscaler flapped: {events}"
        assert pool.desired <= peak_desired - 1
        # the alert interval is PAIRED: a rising edge and a falling edge
        eng_reg = engine.registry
        fired = _counter_values(eng_reg, "tdl_alerts_fired_total")
        cleared = _counter_values(eng_reg, "tdl_alerts_cleared_total")
        assert fired.get(("inference_queue_depth_hwm",), 0) >= 1
        assert cleared.get(("inference_queue_depth_hwm",), 0) >= 1
        # a killed replica died AND was respawned from the shared cache dir
        deaths = _counter_values(reg, "tdl_worker_deaths_total")
        assert deaths[("replica_crash",)] >= 1
    finally:
        scaler.stop()
        pool.stop()
