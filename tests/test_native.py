"""Native tnd library tests: build via ctypes wrapper, parity vs numpy
fallbacks (SURVEY §2.9 N15/N13 — codecs + C ABI + bindings)."""

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.parallel import compression

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_native_threshold_matches_numpy():
    rs = np.random.RandomState(0)
    g = (rs.randn(10_000) * 1e-3).astype(np.float32)
    enc_n = native.threshold_encode(g, 1e-3)
    flat = g.reshape(-1)
    idx = np.nonzero(np.abs(flat) >= 1e-3)[0]
    enc_p = np.concatenate([[flat.size], ((idx + 1) * np.sign(flat[idx])).astype(np.int64)])
    np.testing.assert_array_equal(enc_n, enc_p.astype(np.int64))
    dec = native.threshold_decode(enc_n, 1e-3)
    assert dec.shape == (10_000,)
    assert np.all(np.sign(dec[idx]) == np.sign(flat[idx]))


def test_native_residual_reconstructs():
    rs = np.random.RandomState(1)
    g = (rs.randn(5_000) * 2e-3).astype(np.float32)
    enc, residual = native.threshold_encode_residual(g, 1e-3)
    dec = native.threshold_decode(enc, 1e-3)
    np.testing.assert_allclose(dec + residual, g, atol=1e-6)


def test_compression_module_uses_native():
    rs = np.random.RandomState(2)
    g = (rs.randn(1_000) * 1e-3).astype(np.float32)
    enc, residual = compression.threshold_residual(g, 1e-3)
    dec = compression.threshold_decode(enc, 1e-3)
    np.testing.assert_allclose(dec + residual, g.reshape(-1), atol=1e-6)


def test_native_csv_parse(tmp_path):
    from deeplearning4j_tpu.data.records import load_csv_f32

    p = tmp_path / "m.csv"
    p.write_text("a,b,c\n1,2.5,-3e2\n4,5,6\n")
    arr = load_csv_f32(str(p), skip_rows=1)
    np.testing.assert_allclose(arr, [[1, 2.5, -300], [4, 5, 6]])
    p2 = tmp_path / "bad.csv"
    p2.write_text("x,y\nfoo,bar\n")
    assert load_csv_f32(str(p2), skip_rows=1) is None


def test_native_csv_trailing_delimiter_rejected(tmp_path):
    """ADVICE r1 (medium): a trailing delimiter must NOT merge rows —
    strtof used to eat the newline as leading whitespace, so
    "1,2,\\n3,4,\\n" silently parsed as one 1x4 row."""
    from deeplearning4j_tpu.data.records import load_csv_f32

    p = tmp_path / "trail.csv"
    p.write_text("1,2,\n3,4,\n")
    assert load_csv_f32(str(p)) is None  # empty trailing field = error

    # trailing spaces/tabs before EOL are padding, not an empty field
    p2 = tmp_path / "pad.csv"
    p2.write_text("1.0,2.0 \n3.0,4.0\t\n")
    arr = load_csv_f32(str(p2))
    np.testing.assert_allclose(arr, [[1.0, 2.0], [3.0, 4.0]])

    # blank lines between rows are skipped
    p3 = tmp_path / "blank.csv"
    p3.write_text("1,2\n\n3,4\n")
    arr = load_csv_f32(str(p3))
    np.testing.assert_allclose(arr, [[1, 2], [3, 4]])
