"""Image ETL pipeline (SURVEY §2.3 D3): decode, dir-label extraction,
augmentation chain, DataSet batching, async prefetch, end-to-end CNN fit."""

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from deeplearning4j_tpu.data import (  # noqa: E402
    AsyncDataSetIterator,
    ColorJitterTransform,
    CropImageTransform,
    FlipImageTransform,
    ImagePreProcessingScaler,
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    ParentPathLabelGenerator,
    PipelineImageTransform,
    RandomCropTransform,
    ResizeImageTransform,
)
from deeplearning4j_tpu.data.records import FileSplit  # noqa: E402


@pytest.fixture
def image_dir(tmp_path):
    """12 images in 3 class dirs, distinguishable by mean color."""
    rs = np.random.RandomState(0)
    for ci, cls in enumerate(["cat", "dog", "fox"]):
        d = tmp_path / cls
        d.mkdir()
        for i in range(4):
            arr = np.full((14, 12, 3), 60 * ci + 40, np.uint8)
            arr += rs.randint(0, 20, arr.shape).astype(np.uint8)
            Image.fromarray(arr).save(d / f"img{i}.png")
    return tmp_path


class TestImageRecordReader:
    def test_reads_chw_float_and_dir_labels(self, image_dir):
        rr = ImageRecordReader(8, 10, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        assert rr.labels() == ["cat", "dog", "fox"]
        n = 0
        while rr.has_next():
            img, label = rr.next()
            assert img.shape == (3, 8, 10) and img.dtype == np.float32
            assert 0 <= label < 3
            n += 1
        assert n == 12

    def test_dataset_iterator_one_hot_nchw(self, image_dir):
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = ImageRecordReaderDataSetIterator(rr, batch_size=5)
        ds = it.next()
        assert ds.features.shape == (5, 3, 8, 8)
        assert ds.labels.shape == (5, 3)
        assert np.all(ds.labels.sum(axis=1) == 1.0)
        total = 5
        while it.has_next():
            total += it.next().features.shape[0]
        assert total == 12
        it.reset()
        assert it.has_next()

    def test_scaler_preprocessor(self, image_dir):
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = ImageRecordReaderDataSetIterator(rr, 12, preprocessor=ImagePreProcessingScaler())
        ds = it.next()
        assert float(np.max(ds.features)) <= 1.0 and float(np.min(ds.features)) >= 0.0

    def test_async_prefetch_wrapping(self, image_dir):
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = AsyncDataSetIterator(ImageRecordReaderDataSetIterator(rr, 4))
        batches = []
        while it.has_next():
            batches.append(it.next())
        assert sum(b.features.shape[0] for b in batches) == 12

    def test_uint8_wire_reader_matches_f32_reader(self, image_dir):
        """Narrow wire format (ISSUE 4): uint8_wire emits HWC uint8 rows;
        cast+transpose host-side reproduces the default f32 CHW rows exactly."""
        rr8 = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator(),
                                uint8_wire=True)
        rr8.initialize(FileSplit(str(image_dir)))
        rrf = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rrf.initialize(FileSplit(str(image_dir)))
        while rr8.has_next():
            u8, lab8 = rr8.next()
            f32, labf = rrf.next()
            assert u8.dtype == np.uint8 and u8.shape == (8, 8, 3)
            assert lab8 == labf
            np.testing.assert_array_equal(
                u8.astype(np.float32).transpose(2, 0, 1), f32)

    def test_decode_pool_persists_across_epochs(self, image_dir):
        """ISSUE 4 satellite: ONE decode pool for the iterator's lifetime —
        reset() must not tear it down (rebuilt executors cost a thread-spawn
        storm per epoch); close() does."""
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = ImageRecordReaderDataSetIterator(rr, 4, num_workers=2)
        list(it)
        pool = it._pool
        assert pool is not None  # workers engaged
        it.reset()
        assert sum(1 for _ in it) == 3  # second epoch works...
        assert it._pool is pool  # ...on the SAME pool
        it.close()
        assert it._pool is None

    def test_num_workers_defaults_to_cpu_count(self, image_dir):
        # ISSUE 6 satellite: the default is the AFFINITY count (what a
        # cgroup/taskset-limited host can actually run), not os.cpu_count()
        from deeplearning4j_tpu.common.environment import host_cpu_count

        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = ImageRecordReaderDataSetIterator(rr, 4)
        assert it.num_workers == host_cpu_count()

    def test_transform_chain_deterministic_per_seed(self, image_dir):
        chain = PipelineImageTransform([
            ResizeImageTransform(12, 12),
            FlipImageTransform(1, random=True),
            RandomCropTransform(8, 8),
            ColorJitterTransform(0.1, 0.1),
        ])

        def read_all():
            rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator(),
                                   transform=chain, seed=7)
            rr.initialize(FileSplit(str(image_dir)))
            return np.stack([rr.next()[0] for _ in range(12)])

        a, b = read_all(), read_all()
        np.testing.assert_array_equal(a, b)  # same seed → same augmentation
        assert a.shape == (12, 3, 8, 8)

    def test_crop_transform_shrinks(self):
        rs = np.random.RandomState(3)
        img = rs.randint(0, 255, (20, 20, 3), np.uint8)
        out = CropImageTransform(4).transform(img, rs)
        assert out.shape[0] <= 20 and out.shape[1] <= 20

    def test_cnn_learns_from_image_pipeline(self, image_dir):
        """End-to-end: images on disk → pipeline → CNN fit → labels learned
        (classes are separable by mean color)."""
        from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import (
            ConvolutionLayer,
            GlobalPoolingLayer,
            InputType,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (
            NeuralNetConfiguration.Builder()
            .seed(1)
            .updater(Adam(5e-2))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3), activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        rr = ImageRecordReader(8, 8, 3, ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(image_dir)))
        it = ImageRecordReaderDataSetIterator(rr, 12, preprocessor=ImagePreProcessingScaler())
        net.fit(it, epochs=40)
        rr.reset()
        ev = net.evaluate(ImageRecordReaderDataSetIterator(
            rr, 12, preprocessor=ImagePreProcessingScaler()))
        assert ev.accuracy() > 0.9, ev.accuracy()


class TestVideoReaders:
    def test_gif_video_reader(self, tmp_path):
        from PIL import Image

        from deeplearning4j_tpu.data import VideoRecordReader
        from deeplearning4j_tpu.data.image import ParentPathLabelGenerator
        from deeplearning4j_tpu.data.records import FileSplit

        d = tmp_path / "walk"
        d.mkdir()
        rs = np.random.RandomState(0)
        frames = [Image.fromarray(rs.randint(0, 255, (12, 10, 3), dtype=np.uint8))
                  for _ in range(5)]
        frames[0].save(str(d / "v.gif"), save_all=True,
                       append_images=frames[1:])
        rr = VideoRecordReader(8, 8, 3, start_frame=1, num_frames=3,
                               label_generator=ParentPathLabelGenerator())
        rr.initialize(FileSplit(str(tmp_path)))
        rec = rr.next()
        assert rec[0].shape == (3, 3, 8, 8)   # [T,C,H,W]
        assert rec[1] == 0 and rr.labels() == ["walk"]

    def test_frame_directory_reader(self, tmp_path):
        from PIL import Image

        from deeplearning4j_tpu.data import FrameDirectoryRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        rs = np.random.RandomState(1)
        # class dirs above clips; 12 frames named by ffmpeg's %d convention
        # (1..12 unpadded: a lexicographic sort would scramble them)
        for cls, vid in (("walk", "clip1"), ("run", "clip1")):
            d = tmp_path / cls / vid
            d.mkdir(parents=True)
            for t in range(1, 13):
                Image.fromarray(np.full((6, 6, 3), t, dtype=np.uint8)).save(
                    str(d / f"{t}.png"))
        rr = FrameDirectoryRecordReader(6, 6, 3).initialize(FileSplit(str(tmp_path)))
        assert rr.labels() == ["run", "walk"]   # class vocab, no clip collision
        seq, lab = rr.next()
        assert seq.shape == (12, 3, 6, 6)
        # natural frame order: frame t has constant pixel value t
        np.testing.assert_allclose(seq[:, 0, 0, 0], np.arange(1, 13))
        seq2, lab2 = rr.next()
        assert {lab, lab2} == {0, 1} and not rr.has_next()
