"""Cost-model-balanced pipeline parallelism (ISSUE 19).

The tentpole acceptance, pinned as tier-1 tests:

- pipelined-vs-single-device loss parity at 1e-6 RELATIVE over a real
  ``pipe=2`` CPU mesh (fp32 compute: bf16's 1-ULP encode jitter is 3e-2
  at loss magnitude 8 and would make any 1e-6 bar meaningless);
- GPipe and 1F1B are token-identical: bitwise-equal losses, gradients
  equal to AD noise;
- the schedule's bubble is pinned STRUCTURALLY (scan trip counts in the
  jaxpr: forward fills+drains in ``M+S-1`` ticks, the 1F1B backward in
  ``M+2S-1``) — no flaky wall-clock asserts for a compile-time property;
- stage partitions come from the min-max cost partitioner (hand-computed
  pins), ragged depth without boundaries fails LOUDLY naming both
  numbers, measured skew re-partitions via the same partitioner;
- a ``pipe=2`` checkpoint restores onto ``fsdp=2`` (and back) BITWISE
  via ``reshard=True``, and refuses without it;
- peak temp bytes under remat stop scaling with depth beyond the
  param-linear floor (grad accumulators scale with L by construction —
  the honest flatness claim is about the ACTIVATION slope).
"""

import ast
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_params, loss_fn)
from deeplearning4j_tpu.monitoring import flight, get_registry
from deeplearning4j_tpu.monitoring.costmodel import (balance_stages,
                                                     stage_costs,
                                                     xla_step_cost)
from deeplearning4j_tpu.monitoring.flight import FlightRecorder
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel.partition import (PipelinePartitioner,
                                                   SpecLayout, largest_layout)
from deeplearning4j_tpu.parallel.pipeline import (PipelineParallelTrainer,
                                                  _PipelineNet,
                                                  canonical_pp_params,
                                                  pipeline_transformer_params,
                                                  stage_index_map,
                                                  transformer_pp_loss_fn,
                                                  uniform_boundaries)
from deeplearning4j_tpu.parallel.supervisor import GangSupervisor
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _cfg(n_layers=6, d_model=16, seq=32, remat=False):
    return TransformerConfig(
        vocab_size=64, max_len=seq, d_model=d_model, n_heads=2,
        n_layers=n_layers, d_ff=2 * d_model, dropout=0.0,
        param_dtype=jnp.float32, compute_dtype=jnp.float32, remat=remat)


def _batch(cfg, B=8, T=16, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "tokens": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
        "labels": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
    }


def _mesh(dp=2, pipe=2):
    devs = np.array(jax.devices()[: dp * pipe]).reshape(dp, pipe)
    return Mesh(devs, ("dp", "pipe"))


def _counter_value(name):
    snap = get_registry().snapshot().get(name) or {}
    return sum(s["value"] for s in snap.get("series") or [])


# ------------------------------------------------- cost-model stage partition


class TestStagePartition:
    def test_min_max_split_matches_hand_computed(self):
        # [1,1,1,3] @ 2: cut@3 -> max(3,3)=3 beats cut@2 -> max(2,4)=4
        assert balance_stages([1, 1, 1, 3], 2) == [(0, 3), (3, 4)]
        assert stage_costs([1, 1, 1, 3], [(0, 3), (3, 4)]) == [3.0, 3.0]
        # heavy head: one fat layer alone, the three light ones together
        assert balance_stages([3, 1, 1, 1], 2) == [(0, 1), (1, 4)]
        # uniform costs recover the uniform split
        assert balance_stages([1] * 6, 2) == [(0, 3), (3, 6)]
        # 2x-skewed front half moves one layer across the cut
        assert balance_stages([2, 2, 2, 1, 1, 1], 2) == [(0, 2), (2, 6)]

    def test_tied_splits_resolve_deterministically_earliest_cut(self):
        # [1,1,1] @ 2: cut@1 and cut@2 both cost max=2 — the DP must pin
        # ONE answer or rebalancing would flap between equal splits
        assert balance_stages([1, 1, 1], 2) == [(0, 1), (1, 3)]

    def test_ragged_depth_without_boundaries_raises_naming_both(self):
        cfg = _cfg(n_layers=5)
        params = init_params(jax.random.key(0), cfg)
        with pytest.raises(ValueError) as ei:
            pipeline_transformer_params(params, 2)
        msg = str(ei.value)
        assert "5 layers" in msg and "2 pipeline stages" in msg
        assert "balance_stages" in msg  # the fix is named, not just the crash

    def test_ragged_depth_with_cost_boundaries_works(self):
        cfg = _cfg(n_layers=5)
        params = init_params(jax.random.key(0), cfg)
        bounds = balance_stages([1.0] * 5, 2)
        out = pipeline_transformer_params(params, 2, boundaries=bounds)
        # canonical [L, ...] passthrough — the staged view is built in the
        # compiled step from the static index map, not here
        assert jax.tree.leaves(out["blocks"])[0].shape[0] == 5

    def test_uniform_boundaries_and_index_map_validation(self):
        assert uniform_boundaries(6, 2) == [(0, 3), (3, 6)]
        idx, valid = stage_index_map([(0, 2), (2, 5)])
        assert idx.shape == (2, 3) and valid.shape == (2, 3)
        assert valid.tolist() == [[1.0, 1.0, 0.0], [1.0, 1.0, 1.0]]
        with pytest.raises(ValueError, match="contiguous"):
            stage_index_map([(0, 2), (3, 5)])

    def test_largest_layout_claims_pipe_first(self):
        assert largest_layout(8, pipe=2) == SpecLayout(
            data=1, fsdp=4, tp=1, pipe=2)
        # non-dividing pipe preference degrades instead of failing
        assert largest_layout(7, pipe=2) == SpecLayout(data=1, fsdp=7, tp=1)
        assert largest_layout(8, pipe=2).build_mesh().devices.size == 8

    def test_supervisor_carries_pipe_preference(self, tmp_path):
        sup = GangSupervisor("mod:fn", n_processes=2, pipe_stages=2,
                             workdir=str(tmp_path))
        assert sup.pipe_stages == 2


# --------------------------------------------------------------- loss parity


class TestLossParity:
    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_pipelined_loss_matches_single_device_1e6(self, schedule):
        cfg = _cfg(n_layers=6)
        params = init_params(jax.random.key(0), cfg)
        batch = _batch(cfg)
        ref = float(jax.jit(lambda p, b: loss_fn(p, b, cfg))(params, batch))

        mesh = _mesh(dp=2, pipe=2)
        bounds = balance_stages([1.0] * 6, 2)
        pp_loss = transformer_pp_loss_fn(cfg, 4, mesh, pipe_axis="pipe",
                                         schedule=schedule, boundaries=bounds)
        got = float(jax.jit(pp_loss)(canonical_pp_params(params), batch))
        assert abs(got - ref) / abs(ref) <= 1e-6

    def test_gpipe_and_1f1b_token_identical(self):
        """Same fill-drain forward — losses BITWISE equal; the 1F1B
        custom-vjp backward agrees with GPipe's AD transpose to AD noise."""
        cfg = _cfg(n_layers=6)
        pparams = canonical_pp_params(init_params(jax.random.key(0), cfg))
        batch = _batch(cfg)
        mesh = _mesh(dp=2, pipe=2)
        bounds = balance_stages([1.0] * 6, 2)

        losses, grads = {}, {}
        for schedule in ("gpipe", "1f1b"):
            f = transformer_pp_loss_fn(cfg, 4, mesh, pipe_axis="pipe",
                                       schedule=schedule, boundaries=bounds)
            l, g = jax.jit(jax.value_and_grad(f))(pparams, batch)
            losses[schedule], grads[schedule] = float(l), g
        assert losses["gpipe"] == losses["1f1b"]  # bitwise
        for a, b in zip(jax.tree.leaves(grads["gpipe"]),
                        jax.tree.leaves(grads["1f1b"])):
            scale = max(1.0, float(jnp.max(jnp.abs(a))))
            assert float(jnp.max(jnp.abs(a - b))) / scale <= 1e-8


# ------------------------------------------------- schedule structure (ticks)


def _scan_lengths(jaxpr):
    """All ``lax.scan`` trip counts in a jaxpr, recursively."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(int(eqn.params["length"]))
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:
                    out += _scan_lengths(inner)
                elif hasattr(sub, "eqns"):
                    out += _scan_lengths(sub)
    return out


class TestScheduleTicks:
    """The bubble of a fill-drain schedule is a COMPILE-TIME property: the
    tick scan's trip count. Pinning it in the jaxpr proves the measured
    bubble can't exceed the analytic bound by construction — (ticks - M)
    idle slots out of ticks — without a single wall-clock measurement."""

    def test_forward_runs_m_plus_s_minus_1_ticks(self):
        cfg = _cfg(n_layers=6)
        pparams = canonical_pp_params(init_params(jax.random.key(0), cfg))
        batch = _batch(cfg)
        mesh = _mesh(dp=2, pipe=2)
        bounds = balance_stages([1.0] * 6, 2)
        M, S = 4, 2
        for schedule in ("gpipe", "1f1b"):
            f = transformer_pp_loss_fn(cfg, M, mesh, pipe_axis="pipe",
                                       schedule=schedule, boundaries=bounds)
            lengths = _scan_lengths(jax.make_jaxpr(f)(pparams, batch).jaxpr)
            assert M + S - 1 in lengths, (schedule, lengths)

    def test_1f1b_backward_runs_m_plus_2s_minus_1_ticks(self):
        cfg = _cfg(n_layers=6)
        pparams = canonical_pp_params(init_params(jax.random.key(0), cfg))
        batch = _batch(cfg)
        mesh = _mesh(dp=2, pipe=2)
        bounds = balance_stages([1.0] * 6, 2)
        M, S = 4, 2
        lengths = {}
        for schedule in ("gpipe", "1f1b"):
            f = transformer_pp_loss_fn(cfg, M, mesh, pipe_axis="pipe",
                                       schedule=schedule, boundaries=bounds)
            lengths[schedule] = _scan_lengths(
                jax.make_jaxpr(jax.grad(f))(pparams, batch).jaxpr)
        # 1F1B's combined bwd+recompute scan: one pass of M + 2S - 1 ticks
        assert M + 2 * S - 1 in lengths["1f1b"], lengths["1f1b"]
        # GPipe has no such scan — its backward is the AD transpose of the
        # forward's M + S - 1 tick loop
        assert M + 2 * S - 1 not in lengths["gpipe"], lengths["gpipe"]


# ------------------------------------------------------------------- trainer


class TestPipelineTrainer:
    def test_guard_plain_trainer_rejects_pipe_layout(self):
        cfg = _cfg(n_layers=6)
        net = _PipelineNet(canonical_pp_params(init_params(jax.random.key(0), cfg)))
        with pytest.raises(ValueError, match="pipe"):
            ParallelTrainer(net, mesh_layout=PipelinePartitioner(
                SpecLayout(data=4, pipe=2)))

    def test_pipeline_trainer_rejects_pipe_1(self):
        cfg = _cfg(n_layers=6)
        with pytest.raises(ValueError, match="pipe"):
            PipelineParallelTrainer(
                init_params(jax.random.key(0), cfg), cfg, Adam(1e-3),
                SpecLayout(data=4, fsdp=2), n_microbatches=4)

    def test_trains_profiles_and_rebalances(self, tmp_path, monkeypatch):
        """One trainer exercised end to end (compiles amortized): cost-model
        boundaries at construction, two real 1F1B steps, measured stage
        seconds within 15% of the cost-model prediction, a forced-skew
        rebalance that MOVES the split + bumps the counter + records the
        flight event, and a post-rebalance step through the recompiled
        index map."""
        cfg = _cfg(n_layers=6)
        trainer = PipelineParallelTrainer(
            init_params(jax.random.key(0), cfg), cfg, Adam(1e-3),
            SpecLayout(data=4, pipe=2), n_microbatches=4, schedule="1f1b")
        assert trainer.boundaries == [(0, 3), (3, 6)]  # balanced uniform

        # B=16: microbatch size (B/M = 4) must divide the data axis (4)
        batch = _batch(cfg, B=16)
        trainer._fit_batch(batch)
        l0 = float(trainer.net.score_)
        trainer._fit_batch(batch)
        l1 = float(trainer.net.score_)
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
        assert trainer.net.iteration == 2

        # measured per-stage seconds vs the cost model: uniform layers,
        # 3|3 split -> predicted fractions 0.5/0.5; measured must agree
        # within the 15% acceptance bar (compared as fractions so a
        # loaded CI host's common slowdown divides out)
        times = trainer.profile_stages(seq=32, batch_size=2, repeats=6)
        pred = trainer.predicted_stage_costs()
        m_frac = [t / sum(times) for t in times]
        p_frac = [c / sum(pred) for c in pred]
        for m, p in zip(m_frac, p_frac):
            assert abs(m - p) / p <= 0.15, (times, pred)

        # balanced timings -> no rebalance
        assert trainer.maybe_rebalance([1.0, 1.0]) is None
        assert trainer.boundaries == [(0, 3), (3, 6)]

        # forced 2x skew on stage 0 -> the partitioner moves one layer
        rec = FlightRecorder(proc="pp-test")
        flight.set_flight_recorder(rec)
        try:
            before = _counter_value("tdl_pipe_rebalances_total")
            new = trainer.maybe_rebalance([2.0, 1.0])
            assert new == [(0, 2), (2, 6)]
            assert trainer.boundaries == new
            assert _counter_value("tdl_pipe_rebalances_total") == before + 1
            evs = [e for e in rec.events() if e["kind"] == "pipe_rebalance"]
            assert len(evs) == 1
            assert evs[0]["old_boundaries"] == [[0, 3], [3, 6]]
            assert evs[0]["new_boundaries"] == [[0, 2], [2, 6]]
            assert evs[0]["skew"] == pytest.approx(2.0 / 1.5)
        finally:
            flight.set_flight_recorder(None)

        # the recompiled step trains on the new split
        trainer._fit_batch(batch)
        assert np.isfinite(float(trainer.net.score_))
        assert trainer.net.iteration == 3


# ----------------------------------------------------- lifecycle: pipe↔fsdp


class TestPipeFsdpReshard:
    def test_pipe2_to_fsdp2_roundtrip_bitwise(self, tmp_path):
        """A pipe=2 checkpoint restores onto fsdp=2 bitwise with
        ``reshard=True`` (both layouts chunk the same leading layer dim),
        refuses loudly without it, and survives the round trip back."""
        cfg = _cfg(n_layers=6)
        ta = PipelineParallelTrainer(
            init_params(jax.random.key(0), cfg), cfg, Adam(1e-3),
            SpecLayout(data=4, pipe=2), n_microbatches=4)
        ta._fit_batch(_batch(cfg, B=16))  # non-trivial params + Adam slots
        ck = ta.checkpointer(str(tmp_path), async_write=False)
        assert ck.save(ta.net)

        def fresh_net(seed):
            p = canonical_pp_params(init_params(jax.random.key(seed), cfg))
            return _PipelineNet(p, Adam(1e-3).init(p))

        # mismatched layout without reshard=True: loud refusal, not mixing
        fsdp_part = PipelinePartitioner(SpecLayout(data=4, fsdp=2))
        nb = fresh_net(7)
        from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer
        with pytest.raises(ValueError) as ei:
            TrainingCheckpointer(str(tmp_path), partitioner=fsdp_part,
                                 async_write=False).restore(nb)
        assert "reshard=True" in str(ei.value)

        # pipe=2 -> fsdp=2, bitwise
        assert TrainingCheckpointer(str(tmp_path), partitioner=fsdp_part,
                                    async_write=False,
                                    reshard=True).restore(nb)
        for a, b in zip(jax.tree.leaves(ta.net.params_),
                        jax.tree.leaves(nb.params_)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(ta.net.updater_state),
                        jax.tree.leaves(nb.updater_state)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

        # and back: fsdp=2 -> pipe=2, still bitwise vs the original
        ck2 = TrainingCheckpointer(str(tmp_path / "b"), partitioner=fsdp_part,
                                   async_write=False)
        assert ck2.save(nb)
        nc = fresh_net(9)
        pipe_part = PipelinePartitioner(SpecLayout(data=4, pipe=2))
        assert TrainingCheckpointer(str(tmp_path / "b"),
                                    partitioner=pipe_part, async_write=False,
                                    reshard=True).restore(nc)
        for a, b in zip(jax.tree.leaves(ta.net.params_),
                        jax.tree.leaves(nc.params_)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------- remat memory flatness


class TestRematMemory:
    def test_activation_slope_flat_under_remat(self):
        """Temp bytes at 2x depth split into a param-linear floor (grad
        accumulators and the take-view scale with L by construction) plus
        an ACTIVATION slope. Remat's promise is about the second term:
        per added layer, the non-param temp growth must collapse vs the
        no-remat schedule (measured ~0.14x at d_model=128; asserted at
        0.5x with margin). The raw remat ratio at 2x depth is also pinned
        below the no-remat ratio."""
        M, S = 4, 2
        mesh = _mesh(dp=2, pipe=2)
        stats = {}
        for remat in (False, True):
            for L in (4, 8):
                cfg = _cfg(n_layers=L, d_model=64, remat=remat)
                pparams = canonical_pp_params(
                    init_params(jax.random.key(0), cfg))
                batch = _batch(cfg)
                f = transformer_pp_loss_fn(
                    cfg, M, mesh, pipe_axis="pipe", schedule="1f1b",
                    boundaries=balance_stages([1.0] * L, S))
                stats[(remat, L)] = xla_step_cost(
                    jax.jit(jax.grad(f)), pparams, batch)

        def slopes(remat):
            a, b = stats[(remat, 4)], stats[(remat, 8)]
            temp = (b["temp_bytes"] - a["temp_bytes"]) / 4.0
            param = (b["argument_bytes"] - a["argument_bytes"]) / 4.0
            return temp - param, b["temp_bytes"] / a["temp_bytes"]

        excess_nomat, ratio_nomat = slopes(False)
        excess_remat, ratio_remat = slopes(True)
        assert excess_nomat > 0  # no-remat activations DO scale with depth
        assert excess_remat <= 0.5 * excess_nomat, (
            excess_remat, excess_nomat)
        assert ratio_remat < ratio_nomat, (ratio_remat, ratio_nomat)


# ------------------------------------------------------------------ AST lint


_LINT_FILES = ("deeplearning4j_tpu", "bench.py")


def _boundary_literal_offenders(src: str, rel: str):
    """Hardcoded stage-boundary literals: a ``boundaries=[(..)]`` keyword
    or a ``boundaries = [(..)]`` assignment whose value is a LITERAL
    list/tuple. Boundaries must come from the cost partitioner
    (``balance_stages`` / ``transformer_stage_boundaries``) or arrive as
    an explicit argument; a ``# stage-ok: <reason>`` on the line (or the
    line above) justifies genuine fixtures."""
    lines = src.splitlines()

    def _excused(lineno):
        return any("stage-ok" in ln
                   for ln in lines[max(0, lineno - 2):lineno])

    offenders = []
    for node in ast.walk(ast.parse(src, filename=rel)):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if (kw.arg == "boundaries"
                        and isinstance(kw.value, (ast.List, ast.Tuple))
                        and kw.value.elts
                        and not _excused(node.lineno)):
                    offenders.append(f"{rel}:{node.lineno} (call)")
        elif isinstance(node, ast.Assign):
            names = [t.attr if isinstance(t, ast.Attribute) else
                     getattr(t, "id", "") for t in node.targets]
            if ("boundaries" in names
                    and isinstance(node.value, (ast.List, ast.Tuple))
                    and node.value.elts
                    and not _excused(node.lineno)):
                offenders.append(f"{rel}:{node.lineno} (assign)")
    # ast.walk is breadth-first; report in source order
    return sorted(offenders, key=lambda s: int(s.split(":")[1].split()[0]))


def test_no_hardcoded_stage_boundaries_in_package():
    """ISSUE 19 satellite (repo lint): stage boundaries in the package and
    bench come from the cost-model partitioner or an explicit argument —
    one convenient hardcoded split would silently defeat the balancing
    the pipe axis exists for."""
    offenders = []
    for entry in _LINT_FILES:
        path = ROOT / entry
        files = sorted(path.rglob("*.py")) if path.is_dir() else [path]
        for f in files:
            rel = f.relative_to(ROOT).as_posix()
            offenders += _boundary_literal_offenders(f.read_text(), rel)
    assert not offenders, (
        "hardcoded stage-boundary literal (derive it from "
        "monitoring.costmodel.balance_stages / pass it through, or justify "
        f"a fixture with `# stage-ok: <reason>`): {offenders}")


def test_stage_boundary_lint_catches_a_planted_offender():
    planted = (
        "def f(run, bounds):\n"
        "    run(boundaries=[(0, 1), (1, 6)])\n"
        "    run(boundaries=bounds)\n"
        "    run(boundaries=[(0, 3)])  # stage-ok: test fixture\n"
        "    other = 1\n"
        "    boundaries = [(0, 2), (2, 4)]\n"
        "    boundaries = compute()\n"
    )
    hits = _boundary_literal_offenders(planted, "planted.py")
    assert hits == ["planted.py:2 (call)", "planted.py:6 (assign)"]
