"""Multi-process distributed tests — REAL process boundaries.

VERDICT r2 Missing #1: until a 2+ process run exists, the distribution tier
is a simulation. These tests spawn genuine worker processes (each with its
own jax runtime), connect them through the PJRT distributed coordinator
(gloo CPU collectives), and assert:

- the host-side Collectives SPI works across the boundary,
- MultiProcessTrainer data-parallel training matches a single-process run,
- EncodedGradientsAccumulator exchanges encoded gradients between processes,
- kill-one-process → restore-from-checkpoint reproduces the uninterrupted
  run (SURVEY §5.3 preemption story). The MANUAL restart here pins the
  checkpoint semantics; the unattended version — GangSupervisor detects the
  death, kills the gang, and respawns it from `latest` itself — lives in
  test_supervisor.py (ISSUE 3 graduation of this test).

Analog of the reference's local[N] Spark + DummyTransport tiers (SURVEY
§4.4), upgraded to real processes.
"""

import json
import os
import signal
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel import launcher

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")


def _read(out_base, rank):
    with open(out_base + f".rank{rank}") as f:
        return json.load(f)


def _run(target, tmp_path, n=2, dev=2, extra_env=None, timeout=420):
    out = str(tmp_path / "out.json")
    env = {"TDL_MP_OUT": out, "TDL_MATMUL_PRECISION": "float32"}
    env.update(extra_env or {})
    results = launcher.launch(f"{WORKERS}:{target}", n_processes=n,
                              n_local_devices=dev, extra_env=env, timeout=timeout)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    return [_read(out, i) for i in range(n)]


def test_process_collectives_allgather(tmp_path):
    r0, r1 = _run("allgather_blobs", tmp_path)
    for r in (r0, r1):
        assert r["world"] == 2
        assert r["global_devices"] == 4      # 2 procs x 2 local devices
        assert r["local_devices"] == 2
        assert r["gathered_ranks"] == [0, 1]
        assert r["lens"] == [10, 110]        # rank-dependent payloads crossed


def test_multiprocess_dp_matches_single_process(tmp_path):
    r0, r1 = _run("dp_train", tmp_path)
    assert r0["global_devices"] == 4
    # both processes observed the identical replicated model
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=1e-6)
    np.testing.assert_allclose(r0["param_sum"], r1["param_sum"], rtol=1e-6)

    # single-process reference on the SAME global batches
    from deeplearning4j_tpu.data.dataset import DataSet
    from tests.mp_workers import _global_batch, _toy_net

    net = _toy_net()
    ref_losses = []
    for step in range(6):
        x, y = _global_batch(step)
        net.fit(DataSet(x, y))
        ref_losses.append(net.score_)
    np.testing.assert_allclose(r0["losses"], ref_losses, rtol=1e-4, atol=1e-5)
    flat = np.asarray(net.params().numpy(), np.float64)
    np.testing.assert_allclose(r0["param_sum"], flat.sum(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(r0["param_norm"], np.linalg.norm(flat), rtol=1e-4)


def test_encoded_gradient_exchange_across_processes(tmp_path):
    r0, r1 = _run("grad_exchange", tmp_path)
    # both ranks decoded the same summed sparse update
    np.testing.assert_allclose(r0["upd1_sum"], r1["upd1_sum"], rtol=1e-6)
    np.testing.assert_allclose(r0["upd2_sum"], r1["upd2_sum"], rtol=1e-6)
    # residuals differ (each rank carries its own) and are bounded by the
    # total un-shipped gradient mass of the two rounds (each round decodes
    # only ±threshold per surviving entry; the rest carries forward)
    rs = np.random.RandomState(42)
    g_all = rs.randn(2, 257).astype(np.float32) * 0.3
    for rank, r in enumerate((r0, r1)):
        bound = 2 * np.linalg.norm(g_all[rank]) + 1e-6
        assert 0.0 < r["residual_norm"] < bound


def test_kill_one_process_restore_from_checkpoint(tmp_path):
    # manual-restart half of the preemption contract; the supervised
    # (unattended) half is test_supervisor.test_supervisor_recovers_from_injected_crash
    steps, die_at = 8, 4
    base_env = {"TDL_MP_OUT": str(tmp_path / "a.json"),
                "TDL_MP_CKPT": str(tmp_path / "ckpt_a"),
                "TDL_MP_STEPS": str(steps), "TDL_MP_CKPT_EVERY": "2",
                "TDL_MATMUL_PRECISION": "float32"}
    os.makedirs(base_env["TDL_MP_CKPT"])

    # 1) uninterrupted baseline
    results = launcher.launch(f"{WORKERS}:ckpt_train", n_processes=2,
                              n_local_devices=2, extra_env=base_env, timeout=420)
    for r in results:
        assert r.returncode == 0, r.stderr[-3000:]
    base = _read(base_env["TDL_MP_OUT"], 0)
    assert len(base["losses"]) == steps

    # 2) crashing run: rank 1 hard-exits at step 4 (after the step-3 ckpt)
    crash_env = dict(base_env)
    crash_env.update({"TDL_MP_OUT": str(tmp_path / "b.json"),
                      "TDL_MP_CKPT": str(tmp_path / "ckpt_b"),
                      "TDL_MP_DIE_AT": str(die_at)})
    os.makedirs(crash_env["TDL_MP_CKPT"])
    procs = launcher.spawn(f"{WORKERS}:ckpt_train", n_processes=2,
                           n_local_devices=2, extra_env=crash_env)
    # wait for the preempted rank to die, then take down the survivor (the
    # gang-scheduled model: a lost member aborts the whole job)
    deadline = time.monotonic() + 300
    while procs[1].poll() is None and time.monotonic() < deadline:
        time.sleep(0.5)
    assert procs[1].poll() == 17, "rank 1 should have simulated preemption"
    procs[0].send_signal(signal.SIGKILL)
    launcher.wait(procs, timeout=30)

    marker = os.path.join(crash_env["TDL_MP_CKPT"], "latest.json")
    assert os.path.exists(marker), "no checkpoint survived the crash"
    with open(marker) as f:
        resumed_from = json.load(f)["step"]
    assert resumed_from == die_at  # ckpt after step 3 → resume at step 4

    # 3) restart from checkpoint, run to completion
    restore_env = dict(crash_env)
    restore_env["TDL_MP_RESTORE"] = "1"
    restore_env.pop("TDL_MP_DIE_AT")
    results = launcher.launch(f"{WORKERS}:ckpt_train", n_processes=2,
                              n_local_devices=2, extra_env=restore_env, timeout=420)
    for r in results:
        assert r.returncode == 0, r.stderr[-3000:]
    resumed = _read(restore_env["TDL_MP_OUT"], 0)
    assert resumed["start"] == die_at

    # the resumed tail reproduces the uninterrupted loss curve and the final
    # params match (checkpoint captured params + updater state + iteration)
    np.testing.assert_allclose(resumed["losses"], base["losses"][die_at:],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(resumed["param_sum"], base["param_sum"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(resumed["param_norm"], base["param_norm"], rtol=1e-5)


def test_w2v_embedding_shards_across_processes(tmp_path):
    """Cross-process embedding-shard training (VERDICT r3 missing #6): the
    w2v tables shard over a global 2-process × 4-device mesh; after fit the
    read-back tables are identical on both ranks (row sync through the
    compiled collectives) and the embeddings are semantically sane."""
    r0, r1 = _run("w2v_shard_train", tmp_path, n=2, dev=4, timeout=600)
    assert r0["global_devices"] == 8
    assert r0["vocab"] == 64                       # divides the 8-way axis
    assert r0["syn0_hash"] == r1["syn0_hash"]      # shards re-synced identically
    assert r0["syn1_hash"] == r1["syn1_hash"]
    # words that co-occur must embed closer than words that never do
    assert r0["within"] > r0["across"] + 0.1, (r0["within"], r0["across"])


@pytest.mark.slow
def test_fsdp_param_bytes_shrink_with_fsdp_axis(tmp_path):
    """ISSUE 9 acceptance: per-rank param + optimizer-state bytes shrink
    ~linearly with the fsdp axis size, read from the
    ``tdl_param_bytes_per_rank`` gauge each rank publishes. The toy net's
    dims all divide 4, so fsdp=4 sharding is EXACTLY linear:
    rank bytes = total × local_devices / fsdp."""
    (tmp_path / "f4").mkdir()
    (tmp_path / "f1").mkdir()
    env4 = {"TDL_MP_FSDP": "4", "TDL_MP_STEPS": "2"}
    env1 = {"TDL_MP_DATA": "-1", "TDL_MP_FSDP": "1", "TDL_MP_STEPS": "2"}
    r4 = _run("fsdp_train", tmp_path / "f4", extra_env=env4)
    r1 = _run("fsdp_train", tmp_path / "f1", extra_env=env1)

    total = r4[0]["params_bytes_total"]
    local = r4[0]["local_devices"]
    for r in r4:
        assert r["mesh"] == {"data": 1, "fsdp": 4, "tp": 1}
        # every leaf shards 4 ways → exactly total/4 per device copy
        assert r["bytes_params"] == total * local / 4
        # Adam m/v shard identically to their params → exactly 2x
        assert r["bytes_opt"] == 2 * r["bytes_params"]
    for r in r1:
        # fsdp=1 replicates: every local device holds the full tree
        assert r["bytes_params"] == total * local
    # the linear-shrink headline: fsdp=4 holds 1/4 of the replicated bytes
    assert r1[0]["bytes_params"] == 4 * r4[0]["bytes_params"]
    # both gangs actually trained (finite, rank-identical losses)
    np.testing.assert_allclose(r4[0]["losses"], r4[1]["losses"], rtol=1e-6)
    assert np.isfinite(r4[0]["losses"]).all()


@pytest.mark.slow
def test_fsdp_sharded_checkpoint_roundtrip_and_mismatch(tmp_path):
    """ISSUE 9 satellite: a 2-process fsdp gang saves layout-stamped sharded
    checkpoints via TrainingCheckpointer; a FRESH gang with the same layout
    restores with exact param parity (each rank reads only its shards); a
    gang requesting a different layout dies with an error naming both
    layouts (the ROADMAP item 5 setup)."""
    ckdir = str(tmp_path / "ck")
    base = {"TDL_MP_FSDP": "4", "TDL_MP_CKPT": ckdir, "TDL_MP_STEPS": "4",
            "TDL_MP_CKPT_EVERY": "2"}
    for d in ("a", "b"):
        (tmp_path / d).mkdir()
    trained = _run("fsdp_train", tmp_path / "a", extra_env=base)
    restored = _run("fsdp_train", tmp_path / "b",
                    extra_env={**base, "TDL_MP_MODE": "restore"})
    for t, r in zip(trained, restored):
        # exact: same layout means shard files map 1:1 onto the new gang
        assert r["param_sum"] == t["param_sum"]
        assert r["param_norm"] == t["param_norm"]
        assert r["iteration"] == t["iteration"] == 4
        assert r["bytes_params"] == t["bytes_params"]

    # mismatched layout: fsdp=2 x tp=2 over the same devices must refuse
    out = str(tmp_path / "mm.json")
    results = launcher.launch(
        f"{WORKERS}:fsdp_train", n_processes=2, n_local_devices=2,
        extra_env={**base, "TDL_MP_MODE": "restore", "TDL_MP_FSDP": "2",
                   "TDL_MP_TP": "2", "TDL_MP_OUT": out,
                   "TDL_MATMUL_PRECISION": "float32"},
        timeout=420)
    assert any(r.returncode != 0 for r in results)
    blob = "".join(r.stderr for r in results)
    assert "mesh layout mismatch" in blob
    assert "fsdp=4" in blob and "fsdp=2" in blob  # names BOTH layouts


@pytest.mark.slow
def test_cross_topology_gang_restore_parity(tmp_path):
    """ISSUE 14 acceptance (the mp tier of the restore-parity matrix): a
    4-rank fsdp=4 gang saves sharded checkpoints; a 2-rank fsdp=2 gang AND a
    2-rank fsdp=2×tp=2 gang (layout change, 4 devices) restore them with
    ``reshard=True`` — exact param fingerprint parity, each rank reading
    only the saved chunk slices overlapping its addressable shards."""
    ckdir = str(tmp_path / "ck")
    base = {"TDL_MP_FSDP": "4", "TDL_MP_CKPT": ckdir, "TDL_MP_STEPS": "4",
            "TDL_MP_CKPT_EVERY": "2"}
    for d in ("a", "b", "c"):
        (tmp_path / d).mkdir()
    trained = _run("fsdp_train", tmp_path / "a", n=4, dev=1, extra_env=base)
    assert trained[0]["mesh"] == {"data": 1, "fsdp": 4, "tp": 1}

    # 4 ranks -> 2 ranks, same axis shape class (fsdp-only, half the devices)
    down = _run("fsdp_train", tmp_path / "b", n=2, dev=1,
                extra_env={**base, "TDL_MP_MODE": "restore",
                           "TDL_MP_FSDP": "2", "TDL_MP_RESHARD": "1"})
    # 4 ranks -> 2 ranks x 2 devices with an fsdp↔tp layout change
    cross = _run("fsdp_train", tmp_path / "c", n=2, dev=2,
                 extra_env={**base, "TDL_MP_MODE": "restore",
                            "TDL_MP_FSDP": "2", "TDL_MP_TP": "2",
                            "TDL_MP_RESHARD": "1"})
    for restored, mesh in ((down, {"data": 1, "fsdp": 2, "tp": 1}),
                           (cross, {"data": 1, "fsdp": 2, "tp": 2})):
        for t, r in zip(trained, restored):
            # the restored ARRAYS are bitwise-equal (pinned exactly by the
            # tier-1 matrix in tests/test_reshard.py); the device-side
            # fingerprint SUM reduces in sharding-dependent order, so the
            # cross-layout fingerprints agree to f32 rounding, not bit-ly
            np.testing.assert_allclose(r["param_sum"], t["param_sum"],
                                       rtol=2e-6, atol=1e-5)
            np.testing.assert_allclose(r["param_norm"], t["param_norm"],
                                       rtol=2e-6)
            assert r["iteration"] == t["iteration"] == 4
        assert restored[0]["mesh"] == mesh


def test_multiprocess_tp_matches_single_process(tmp_path):
    """Tensor-parallel axis SPANNING the process boundary (r5: VERDICT r4
    weak #7 — the multi-process tier previously proved DP numerics only)."""
    import jax

    r0, r1 = _run("tp_train", tmp_path)
    assert r0["global_devices"] == 4
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=1e-6)

    from jax.sharding import Mesh
    from tests.mp_workers import tp_step_losses

    ref = tp_step_losses(Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                              ("dp", "tp")))
    np.testing.assert_allclose(r0["losses"], ref, rtol=2e-4, atol=1e-5)
