"""YOLOv2 head (C15/C16): loss, NMS, object extraction, TinyYOLO training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.yolo import (
    DetectedObject,
    TinyYOLO,
    Yolo2OutputLayer,
    get_predicted_objects,
    iou,
    nms,
    yolo2_loss,
)

ANCHORS = np.array([[1.0, 1.0], [3.0, 3.0]], np.float32)


def _label(B=2, C=2, H=4, W=4):
    """One object per image: class 0 box at cell (1,2), class 1 at (3,0)."""
    lab = np.zeros((B, 4 + C, H, W), np.float32)
    lab[:, 0:4, 2, 1] = [1.0, 1.8, 2.2, 2.9]   # x1,y1,x2,y2 (grid units)
    lab[:, 4, 2, 1] = 1.0
    lab[:, 0:4, 0, 3] = [2.6, 0.1, 3.9, 1.2]
    lab[:, 5, 0, 3] = 1.0
    return lab


def test_yolo_loss_finite_and_differentiable():
    rs = np.random.RandomState(0)
    pred = jnp.asarray(rs.randn(2, 2 * 7, 4, 4).astype(np.float32))
    lab = jnp.asarray(_label())
    loss = yolo2_loss(pred, lab, ANCHORS)
    assert np.isfinite(float(loss)) and float(loss) > 0
    g = jax.grad(lambda p: yolo2_loss(p, lab, ANCHORS))(pred)
    assert np.all(np.isfinite(np.asarray(g)))
    assert float(jnp.sum(jnp.abs(g))) > 0


def test_yolo_head_learns_synthetic_box():
    """Optimize the raw map directly: loss should drive the responsible
    anchor's prediction onto the gt box."""
    lab = jnp.asarray(_label(B=1))
    pred = jnp.zeros((1, 14, 4, 4), jnp.float32)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(lambda q: yolo2_loss(q, lab, ANCHORS))(p)
        return p - 0.1 * g, l

    losses = []
    for _ in range(400):
        pred, l = step(pred)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.1
    dets = get_predicted_objects(np.asarray(pred), ANCHORS, threshold=0.4)[0]
    assert dets, "no detection above threshold"
    d = dets[0]
    # gt box at cell (1,2): center (1.6, 2.35), w=1.2 h=1.1, class 0
    assert abs(d.center_x - 1.6) < 0.35 and abs(d.center_y - 2.35) < 0.35
    assert d.predicted_class == 0


def test_nms_suppresses_overlaps():
    a = DetectedObject(2.0, 2.0, 2.0, 2.0, 0, 0.9)
    b = DetectedObject(2.2, 2.1, 2.0, 2.0, 0, 0.7)   # overlaps a
    c = DetectedObject(6.0, 6.0, 2.0, 2.0, 0, 0.8)   # far away
    d = DetectedObject(2.1, 2.0, 2.0, 2.0, 1, 0.6)   # other class survives
    kept = nms([a, b, c, d], iou_threshold=0.4)
    assert a in kept and c in kept and d in kept and b not in kept
    assert iou(a, b) > 0.4 and iou(a, c) == 0.0


def test_tinyyolo_builds_and_trains_one_step():
    ty = TinyYOLO(n_classes=2, input_shape=(3, 32, 32),
                  anchors=((1.0, 1.0), (2.0, 2.0)), base_filters=4,
                  downsamples=3)
    net = ty.init()
    from deeplearning4j_tpu.data.dataset import DataSet

    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 32, 32).astype(np.float32)
    lab = _label(B=2, C=2, H=4, W=4)
    s0 = None
    for _ in range(5):
        net._fit_batch(DataSet(x, lab))
        if s0 is None:
            s0 = net.score_
    assert np.isfinite(net.score_)
    assert net.score_ < s0
