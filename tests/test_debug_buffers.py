"""Live-buffer accounting + donation-misuse checks (SURVEY §5.2).

The TPU-build analogs of the reference's sanitizer/workspace-validation
story: HBM leak detection via jax.live_arrays and a post-step assertion
that donated buffers actually died.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.debug import LiveBufferMonitor, donation_guard


def test_monitor_clean_loop_no_leak():
    mon = LiveBufferMonitor(warn_after=5)

    @jax.jit
    def step(x):
        return x * 2.0 + 1.0

    x = jnp.zeros((64,))
    for _ in range(12):
        x = step(x)
        mon.tick()
    mon.assert_no_leak()          # steady state: old buffers die each step
    assert not mon.leak_detected


def test_monitor_flags_growth():
    mon = LiveBufferMonitor(warn_after=4)
    hoard = []
    with pytest.warns(UserWarning, match="buffer count grew"):
        for i in range(8):
            hoard.append(jnp.full((128,), float(i)))   # deliberate retention
            mon.tick()
    assert mon.leak_detected
    with pytest.raises(AssertionError, match="leak"):
        mon.assert_no_leak()
    del hoard


def test_donation_guard_passes_when_donation_works():
    def step(params, x):
        return jax.tree.map(lambda p: p + x.sum(), params)

    jstep = donation_guard(jax.jit(step, donate_argnums=(0,)), (0,))
    params = {"w": jnp.ones((32, 32)), "b": jnp.zeros((32,))}
    x = jnp.ones((4,))
    for _ in range(3):
        params = jstep(params, x)   # fresh tree each call: donation honored
    np.testing.assert_allclose(np.asarray(params["b"]), 12.0)


def test_donation_guard_catches_aliased_input():
    def step(params, x):
        return jax.tree.map(lambda p: p + x.sum(), params)

    jstep = donation_guard(jax.jit(step, donate_argnums=(0,)), (0,))
    params = {"w": jnp.ones((32, 32))}
    keep_alive = params["w"] + 0.0   # a second live use of the same value
    # jax only deletes donated buffers it could reuse; keeping an alias in a
    # COPY does not block donation — to force a survivor, donate an array
    # jit cannot consume: a committed constant reused as a non-donated arg
    out = jstep(params, jnp.ones((4,)))
    assert out  # donation honored here — guard stayed quiet
    del keep_alive

    # direct misuse: re-calling with the ALREADY-DONATED tree raises jax's
    # deleted-buffer error before the guard, proving buffers really died
    with pytest.raises(Exception):
        jstep(params, jnp.ones((4,)))


def test_fit_under_debug_env(monkeypatch):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    monkeypatch.setenv("TDL_DEBUG_BUFFERS", "1")
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    ds = DataSet(rs.rand(16, 4).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)])
    net.fit(ds)   # guard wraps the donating step; a healthy fit passes
    net.fit(ds)
    assert np.isfinite(float(net.score()))
