"""Multi-process sharded ETL service tests (ISSUE 6).

Covers the tentpole acceptance surface: zero-copy shared-memory ring handoff
(no batch payload pickling), per-rank shard disjointness + union
completeness across world sizes, cross-process exception propagation with
the original traceback (sticky until reset), worker-death respawn,
persistent decoded-batch cache hits, deterministic replay across a
simulated restart (``state()``/``set_state()``), and fit-loop ``finally``
worker cleanup. The full 2-process GangSupervisor restart parity run is
slow-marked with the rest of the chaos tier.
"""

import dataclasses
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.etl_service import (
    EtlDataSetIterator,
    EtlWorkerError,
    ImageEtlSpec,
    shard_batches,
)
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DevicePrefetchIterator,
)
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    """32 tiny JPEGs in 4 class dirs → 4 batches of 8 at batch_size=8."""
    from PIL import Image

    root = tmp_path_factory.mktemp("etl_imgs")
    rs = np.random.RandomState(0)
    for i in range(32):
        d = root / f"c{i % 4}"
        d.mkdir(exist_ok=True)
        Image.fromarray(rs.randint(0, 255, (40, 40, 3), dtype=np.uint8)).save(
            str(d / f"i{i:02d}.jpg"), quality=85)
    return str(root)


def _spec(image_dir, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("store_pad", 8)
    return ImageEtlSpec.from_directory(image_dir, 24, 24, **kw)


def _drain(it, copy=True):
    out = []
    it.reset()
    while it.has_next():
        ds = it.next()
        out.append((ds.features.copy() if copy else ds.features,
                    ds.labels.copy() if copy else ds.labels))
    return out


# ------------------------------------------------------------------ sharding


@pytest.mark.parametrize("world", [1, 2, 4])
def test_shard_disjoint_and_union_complete(world):
    """Per-rank shards partition the global batch set: pairwise disjoint,
    and (unequalized) their union covers every batch."""
    M = 13
    shards = [shard_batches(M, r, world, equalize=False) for r in range(world)]
    flat = [b for s in shards for b in s]
    assert len(flat) == len(set(flat)) == M          # disjoint + complete
    assert sorted(flat) == list(range(M))
    # equalized: still disjoint, every rank the same length (lockstep gangs)
    eq = [shard_batches(M, r, world) for r in range(world)]
    assert len({len(s) for s in eq}) == 1
    assert len(eq[0]) == M // world
    for r, s in enumerate(eq):
        assert s == shards[r][: M // world]           # deterministic prefix


def test_shard_deterministic_across_calls():
    assert shard_batches(100, 3, 4) == shard_batches(100, 3, 4)
    with pytest.raises(ValueError):
        shard_batches(10, 4, 4)


def test_sharded_specs_cover_stream(image_dir):
    """Union of every rank's (unequalized) batch indices == the single-rank
    stream; per-rank batches decode to the SAME pixels as the world-1 run."""
    spec1 = _spec(image_dir)
    world = 2
    per_rank = [spec1.for_rank(r, world) for r in range(world)]
    covered = sorted(b for s in per_rank
                     for b in shard_batches(s.num_batches, s.rank,
                                            s.world_size, equalize=False))
    assert covered == list(range(spec1.num_batches))
    # batch b decodes identically no matter which rank's spec produces it
    b = 1
    a, la, _ = per_rank[b % world].produce(b, epoch=0, cache=None)
    ref, lr, _ = spec1.produce(b, epoch=0, cache=None)
    np.testing.assert_array_equal(a, ref)
    np.testing.assert_array_equal(la, lr)


# ----------------------------------------------------------- ring + zero-copy


def test_ring_zero_copy_no_payload_pickling(image_dir):
    """Acceptance: the ring handoff adds ZERO payload pickling — every batch
    the consumer sees is a live VIEW into the shared-memory ring (the pixels
    crossed the process boundary in place), and the only pickled traffic is
    the spawn-time spec."""
    it = EtlDataSetIterator(_spec(image_dir), num_workers=2,
                            registry=MetricsRegistry())
    try:
        it.reset()
        seen = 0
        while it.has_next():
            ds = it.next()
            assert ds.features.dtype == np.uint8
            assert ds.features.shape == (8, 24, 24, 3)
            assert np.shares_memory(ds.features, it.ring_payload_view()), \
                "batch is a copy, not a shm ring view"
            assert ds.labels.shape == (8, it.num_classes)
            seen += 1
        assert seen == it.epoch_batches == 4
    finally:
        it.close()


def test_epoch_stream_deterministic_and_augment_varies_by_epoch(image_dir):
    spec = _spec(image_dir)
    it = EtlDataSetIterator(spec, num_workers=2, registry=MetricsRegistry(),
                            zero_copy=False)
    try:
        e0 = _drain(it)
        e1 = _drain(it)
    finally:
        it.close()
    it2 = EtlDataSetIterator(spec, num_workers=1, registry=MetricsRegistry(),
                             zero_copy=False)
    try:
        r0 = _drain(it2)
        r1 = _drain(it2)
    finally:
        it2.close()
    # same stream regardless of worker count — per-(seed, epoch, batch)
    # seeding makes production order-independent
    for (a, la), (b, lb) in zip(e0 + e1, r0 + r1):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
    # augmentation differs across epochs (same composition, new crops/flips)
    assert any(not np.array_equal(a[0], b[0]) for (a, _), (b, _) in zip(e0, e1))


def test_zero_copy_view_valid_until_next_next(image_dir):
    """The documented zero-copy lifetime: a view stays intact until the
    FOLLOWING next() call (the slot is only released then)."""
    it = EtlDataSetIterator(_spec(image_dir), num_workers=1, ring_slots=2,
                            registry=MetricsRegistry())
    try:
        it.reset()
        first = it.next().features
        snap = first.copy()
        time.sleep(0.3)  # workers race ahead into other slots meanwhile
        np.testing.assert_array_equal(first, snap)
    finally:
        it.close()


# ------------------------------------------------------- failure propagation


def test_worker_exception_surfaces_with_traceback_sticky_until_reset(image_dir):
    spec = _spec(image_dir, shuffle=False)
    files = list(spec.files)
    files[3] = os.path.join(image_dir, "missing.jpg")  # poisons batch 0
    bad = dataclasses.replace(spec, files=tuple(files))
    it = EtlDataSetIterator(bad, num_workers=2, registry=MetricsRegistry())
    try:
        it.reset()
        with pytest.raises(EtlWorkerError) as ei:
            while it.has_next():
                it.next()
        # the ORIGINAL worker-side traceback text crossed the boundary
        assert "FileNotFoundError" in str(ei.value)
        assert "missing.jpg" in str(ei.value)
        assert "decode_store_batch" in ei.value.traceback_text
        # sticky: every subsequent call re-raises until reset()
        with pytest.raises(EtlWorkerError):
            it.has_next()
        with pytest.raises(EtlWorkerError):
            it.next()
        it.reset()  # clears the error and restarts the epoch
        assert it.has_next()
    finally:
        it.close()


def test_dead_worker_respawns_and_stream_stays_exact(image_dir):
    """A worker killed hard (no error report) is detected and respawned at
    its next unpublished position; the consumed stream is byte-identical to
    an unfaulted run and the respawn is counted."""
    spec = _spec(image_dir)
    reg = MetricsRegistry()
    it = EtlDataSetIterator(spec, num_workers=2, registry=reg,
                            zero_copy=False)
    try:
        it.reset()
        got = [it.next()]
        os.kill(it._workers[0].proc.pid, signal.SIGKILL)
        while it.has_next():
            got.append(it.next())
        assert it.etl_stats()["worker_respawns"] == 1
        assert reg.get("tdl_etl_worker_respawns_total").value == 1
    finally:
        it.close()
    ref_it = EtlDataSetIterator(spec, num_workers=1,
                                registry=MetricsRegistry(), zero_copy=False)
    try:
        ref = _drain(ref_it)
    finally:
        ref_it.close()
    assert len(got) == len(ref) == 4
    for ds, (f, l) in zip(got, ref):
        np.testing.assert_array_equal(ds.features, f)
        np.testing.assert_array_equal(ds.labels, l)


# ------------------------------------------------------------ decoded cache


def test_persistent_cache_skips_decode_on_second_epoch(image_dir, tmp_path):
    spec = _spec(image_dir, cache_dir=str(tmp_path / "cache"))
    reg = MetricsRegistry()
    it = EtlDataSetIterator(spec, num_workers=2, registry=reg,
                            zero_copy=False)
    try:
        e0 = _drain(it)
        # let producers finish anything in flight, then read the counters
        e1 = _drain(it)
    finally:
        it.close()
    stats = it.etl_stats()
    assert stats["cache_misses"] <= spec.num_batches  # epoch 0 decodes once
    assert stats["cache_hits"] >= spec.num_batches    # epoch ≥2 hits
    assert reg.get("tdl_etl_cache_hits_total").value == stats["cache_hits"]
    # a RESTARTED service (fresh processes) reuses the cache AND reproduces
    # the exact stream
    reg2 = MetricsRegistry()
    it2 = EtlDataSetIterator(spec, num_workers=1, registry=reg2,
                             zero_copy=False)
    try:
        r0 = _drain(it2)
    finally:
        it2.close()
    assert it2.etl_stats()["cache_misses"] == 0
    assert it2.etl_stats()["cache_hits"] >= spec.num_batches
    for (a, la), (b, lb) in zip(e0, r0):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
    assert len(e1) == len(e0)


def test_cache_stale_lock_reclaimed_not_wedged(image_dir, tmp_path):
    """A creation winner SIGKILLed before meta.json lands (the gang-teardown
    chaos model) must not poison the cache dir: the stale lock is reclaimed
    and the next comer builds the cache."""
    spec = _spec(image_dir, cache_dir=str(tmp_path))
    d = os.path.join(str(tmp_path), spec.fingerprint())
    os.makedirs(d)
    lock = os.path.join(d, ".lock")
    with open(lock, "w"):
        pass
    old = time.time() - 120.0  # well past the staleness horizon
    os.utime(lock, (old, old))
    cache = spec.open_cache()  # reclaims the dead winner's lock + builds
    assert cache.done_count() == 0
    assert not os.path.exists(lock)
    assert os.path.exists(os.path.join(d, "meta.json"))


def test_cache_key_changes_with_etl_config(image_dir, tmp_path):
    a = _spec(image_dir, cache_dir=str(tmp_path))
    b = dataclasses.replace(a, store_pad=4)
    c = dataclasses.replace(a, seed=a.seed + 1)
    assert a.fingerprint() == _spec(image_dir, cache_dir=str(tmp_path)).fingerprint()
    assert len({a.fingerprint(), b.fingerprint(), c.fingerprint()}) == 3
    # distinct configs land in distinct subdirectories — no cross-pollution
    ca, cb = a.open_cache(), b.open_cache()
    assert ca.dir != cb.dir


# ------------------------------------------------- restart replay (state)


def test_set_state_resumes_exact_stream_after_close(image_dir):
    """The GangSupervisor restart contract in miniature: consume part of the
    stream, tear the service down (the 'crash'), rebuild from state() — the
    combined stream is byte-identical to an uninterrupted run, INCLUDING
    through the __iter__ protocol's leading reset()."""
    spec = _spec(image_dir)
    ref_it = EtlDataSetIterator(spec, num_workers=2,
                                registry=MetricsRegistry(), zero_copy=False)
    try:
        ref = _drain(ref_it) + _drain(ref_it)  # two epochs
    finally:
        ref_it.close()

    it = EtlDataSetIterator(spec, num_workers=2, registry=MetricsRegistry(),
                            zero_copy=False)
    got = []
    try:
        it.reset()
        for _ in range(3):  # through the epoch boundary would be pos 4
            ds = it.next()
            got.append((ds.features, ds.labels))
        state = it.state()
    finally:
        it.close()
    assert state == {"epoch": 0, "pos": 3}

    it2 = EtlDataSetIterator(spec, num_workers=1, registry=MetricsRegistry(),
                             zero_copy=False)
    try:
        it2.set_state(state)
        # the for-protocol fires reset() first — must NOT rewind the resume
        for ds in it2:
            got.append((ds.features, ds.labels))
        for ds in it2:  # next epoch
            got.append((ds.features, ds.labels))
    finally:
        it2.close()
    assert len(got) == len(ref)
    for (a, la), (b, lb) in zip(got, ref):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)


def test_set_state_at_epoch_boundary_resumes_without_leading_reset(image_dir):
    """Regression: a checkpoint taken exactly at an epoch boundary restores
    to (epoch e, pos 0); the worker consume pattern (`if not has_next():
    reset()` with NO leading reset) must flow into epoch e and the boundary
    reset into e+1 — the resume guard must not swallow the boundary."""
    spec = _spec(image_dir)
    ref_it = EtlDataSetIterator(spec, num_workers=1,
                                registry=MetricsRegistry(), zero_copy=False)
    try:
        _drain(ref_it)           # epoch 0
        ref_e1 = _drain(ref_it)  # epoch 1
        ref_e2 = _drain(ref_it)  # epoch 2
    finally:
        ref_it.close()
    it = EtlDataSetIterator(spec, num_workers=1, registry=MetricsRegistry(),
                            zero_copy=False)
    got = []
    try:
        it.set_state({"epoch": 1, "pos": 0})
        for _ in range(2 * it.epoch_batches):  # epoch 1 THROUGH epoch 2
            if not it.has_next():
                it.reset()
            ds = it.next()
            got.append((ds.features, ds.labels))
        assert it.state() == {"epoch": 3, "pos": 0}
    finally:
        it.close()
    for (a, la), (b, lb) in zip(got, ref_e1 + ref_e2):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)


def test_fit_replay_param_parity_after_simulated_restart(image_dir):
    """Param-parity acceptance, single-process: train on the ETL stream,
    'crash' mid-epoch (close + rebuild from state), finish — final params
    exactly match the unfaulted run."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import DataSetIterator
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd

    spec = _spec(image_dir)

    class _Flat(DataSetIterator):
        """uint8 NHWC → flat float batches for a toy dense net."""

        def __init__(self, base):
            self.base = base

        def has_next(self):
            return self.base.has_next()

        def reset(self):
            self.base.reset()

        def batch(self):
            return self.base.batch()

        def next(self):
            ds = self.base.next()
            x = ds.features.reshape(ds.features.shape[0], -1)
            return DataSet(x.astype(np.float32) / 255.0, ds.labels)

    def net():
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.05))
                .list()
                .layer(DenseLayer(n_in=24 * 24 * 3, n_out=16,
                                  activation="tanh"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(24 * 24 * 3))
                .build())
        return MultiLayerNetwork(conf).init()

    def params(n):
        return np.asarray(n.params().numpy(), np.float64)

    # unfaulted reference: one full epoch
    ref_net = net()
    ref_it = EtlDataSetIterator(spec, num_workers=2,
                                registry=MetricsRegistry(), zero_copy=False)
    try:
        ref_net.fit(_Flat(ref_it))
    finally:
        ref_it.close()
    ref = params(ref_net)

    # faulted run: crash after 2 batches, restore, resume from state
    n2 = net()
    it = EtlDataSetIterator(spec, num_workers=2, registry=MetricsRegistry(),
                            zero_copy=False)
    try:
        it.reset()
        for _ in range(2):
            ds = _Flat(it).next()
            n2._fit_batch(ds)
        state = it.state()
    finally:
        it.close()  # the crash
    it2 = EtlDataSetIterator(spec, num_workers=1, registry=MetricsRegistry(),
                             zero_copy=False)
    try:
        it2.set_state(state)
        n2.fit(_Flat(it2))  # __iter__ reset keeps the resume position
    finally:
        it2.close()
    np.testing.assert_array_equal(params(n2), ref)


# ----------------------------------------------------- fit-loop worker hygiene


class _Boom(Exception):
    pass


def test_fit_closes_async_workers_on_midepoch_exception():
    """ISSUE 6 satellite: an exception mid-epoch must not leak the prefetch
    worker thread until GC — fit's finally joins it."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd

    rs = np.random.RandomState(0)
    sets = [DataSet(rs.rand(4, 6).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rs.randint(0, 3, 4)])
            for _ in range(50)]

    class _Poison(ListDataSetIterator):
        def next(self):
            ds = super().next()
            if self._pos == 3:
                raise _Boom("etl blows up mid-epoch")
            return ds

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    nets = MultiLayerNetwork(conf).init()
    before = {t.ident for t in threading.enumerate()}
    it = AsyncDataSetIterator(_Poison(sets), queue_size=2)
    with pytest.raises(_Boom):
        nets.fit(it)
    assert it._thread is None  # joined by fit's finally, not leaked to GC
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.is_alive()]
    assert not leaked, leaked


def test_etl_iterator_resumes_after_fit_finally_close(image_dir):
    """The fit-loop close must not lose the stream: EtlDataSetIterator is
    restart-safe — close() then continued consumption resumes at the same
    position with fresh worker processes."""
    it = EtlDataSetIterator(_spec(image_dir), num_workers=1,
                            registry=MetricsRegistry(), zero_copy=False)
    try:
        it.reset()
        a = it.next().features
        it.close()           # what a fit finally does
        assert not it._started
        b = it.next().features  # lazy respawn, next position
        assert not np.array_equal(a, b)
        assert it.state() == {"epoch": 0, "pos": 2}
    finally:
        it.close()


def test_trainer_sharded_etl_wiring(image_dir):
    """ParallelTrainer.sharded_etl re-ranks the spec to the trainer's
    (rank, world) and wraps it in the mesh-sharded device prefetcher."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    trainer = ParallelTrainer(MultiLayerNetwork(conf).init(),
                              build_mesh(data=-1))
    spec = _spec(image_dir).for_rank(3, 7)  # stale placement gets replaced
    pre = trainer.sharded_etl(spec, num_workers=1)
    assert isinstance(pre, DevicePrefetchIterator)
    assert pre._base.spec.rank == 0 and pre._base.spec.world_size == 1
    assert pre._sharding is not None  # one-shot mesh placement wired
    # DevicePrefetchIterator stages to device before queueing → the shm
    # ring view's lifetime contract holds and zero-copy stays on
    assert pre._base.zero_copy
    pre.close()  # lazy service: nothing spawned, close is a no-op
    bare = trainer.sharded_etl(spec, num_workers=1, prefetch=0)
    assert isinstance(bare, EtlDataSetIterator)
    bare.close()


def test_multiprocess_trainer_sharded_etl_copies_out_of_ring(image_dir):
    """MultiProcessTrainer's prefetch wrapper is a plain AsyncDataSetIterator
    that BUFFERS host batches across base.next() calls — a zero-copy ring
    view queued there could be overwritten in place by a fast worker, so
    sharded_etl must hand out copies on that path (and may stay zero-copy
    for the unbuffered prefetch=0 path)."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    mpt = MultiProcessTrainer(MultiLayerNetwork(conf).init(),
                              build_mesh(data=-1))
    pre = mpt.sharded_etl(_spec(image_dir), num_workers=1)
    assert isinstance(pre, AsyncDataSetIterator)
    assert pre._base.zero_copy is False  # buffered host views ⇒ copies
    pre.close()
    bare = mpt.sharded_etl(_spec(image_dir), num_workers=1, prefetch=0)
    assert bare.zero_copy is True  # unbuffered direct consumption: safe
    bare.close()


def test_async_close_propagates_to_restartable_base(image_dir):
    it = EtlDataSetIterator(_spec(image_dir), num_workers=1,
                            registry=MetricsRegistry())
    pre = DevicePrefetchIterator(it, buffer_size=2,
                                 registry=MetricsRegistry())
    assert pre.has_next()
    assert it._started
    pre.close()
    assert not it._started  # ETL worker processes + shm released too


# ---------------------------------------------------- gang restart (slow tier)


@pytest.mark.slow
def test_gang_restart_replays_sharded_etl_with_param_parity(image_dir,
                                                            tmp_path):
    """Acceptance: per-rank sharded ETL replays deterministically across a
    GangSupervisor restart — a crash-injected 2-rank gang finishes
    unattended with final params EXACTLY matching the unfaulted gang."""
    from deeplearning4j_tpu.parallel import GangSupervisor, launcher

    def run(fault, sub):
        out = str(tmp_path / sub / "out.json")
        os.makedirs(str(tmp_path / sub), exist_ok=True)
        env = {"TDL_MP_OUT": out,
               "TDL_MP_CKPT": str(tmp_path / sub / "ckpt"),
               "TDL_ETL_DIR": image_dir,
               "TDL_ETL_CACHE": str(tmp_path / "shared_cache"),
               "TDL_MP_CKPT_EVERY": "2",
               "TDL_MATMUL_PRECISION": "float32"}
        os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
        if fault:
            env["TDL_FAULT_SPEC"] = fault
        sup = GangSupervisor(f"{WORKERS}:etl_train", n_processes=2,
                             n_local_devices=2, extra_env=env,
                             workdir=str(tmp_path / sub / "gang"),
                             heartbeat_interval=0.0, backoff_base=0.1,
                             kill_grace=1.0, startup_grace=300.0,
                             registry=MetricsRegistry())
        results = sup.run(timeout=540.0)
        for r in results:
            assert r.returncode == 0, \
                f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
        with open(out + ".rank0") as f:
            return json.load(f), sup

    clean, sup0 = run(None, "clean")
    assert sup0.restarts == 0
    faulted, sup1 = run("crash@iter=5,rank=1", "faulted")
    assert sup1.restarts >= 1
    assert faulted["incarnation"] >= 1
    assert faulted["start"] == 4  # ckpt after step 3 survived; crash was at 5
    # same batch stream: every step the restarted incarnation ran consumed
    # byte-identical batches to the unfaulted gang's same step
    assert faulted["step_hashes"]
    for step, digest in faulted["step_hashes"].items():
        assert clean["step_hashes"][step] == digest, f"step {step} diverged"
    # exact param parity with the unfaulted run
    np.testing.assert_array_equal(
        np.asarray(faulted["param_tail"]), np.asarray(clean["param_tail"]))
    assert faulted["param_sum"] == clean["param_sum"]
