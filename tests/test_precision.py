"""Mixed-precision (AMP) policy tests — TDL_MATMUL_PRECISION=bfloat16.

Covers VERDICT r1 Weak #3 (the flag used to be dead): masters stay fp32,
grads arrive fp32, loss is finite and close to the fp32 run, BN running
stats stay fp32.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.environment import env
from deeplearning4j_tpu.common.precision import amp_enabled, compute_dtype
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@pytest.fixture
def bf16_policy():
    old = env().matmul_precision
    env().set("matmul_precision", "bfloat16")
    yield
    env().set("matmul_precision", old)


def _small_cnn():
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(7)
        .list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3), stride=(1, 1), activation="relu"))
        .layer(BatchNormalization())
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional(8, 8, 3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def test_policy_flag_resolution(bf16_policy):
    assert compute_dtype() == jnp.bfloat16
    assert amp_enabled(jnp.float32)
    assert not amp_enabled(jnp.bfloat16)  # explicit-dtype models opt out
    env().set("matmul_precision", "float32")
    assert compute_dtype() == jnp.float32
    assert not amp_enabled(jnp.float32)


def test_amp_step_masters_stay_fp32(bf16_policy):
    net = _small_cnn()
    rs = np.random.RandomState(0)
    x = rs.rand(4, 3, 8, 8).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 4)]
    net.fit(x, y, epochs=2)
    assert np.isfinite(net.score_)
    for layer_params in net.params_.values():
        for w in layer_params.values():
            assert w.dtype == jnp.float32
    for st in net.bn_state.values():
        assert st["mean"].dtype == jnp.float32
        assert st["var"].dtype == jnp.float32


def test_amp_loss_close_to_fp32():
    rs = np.random.RandomState(1)
    x = rs.rand(8, 3, 8, 8).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 8)]

    net32 = _small_cnn()
    net32.fit(x, y)
    loss32 = net32.score_

    env().set("matmul_precision", "bfloat16")
    try:
        net16 = _small_cnn()
        net16.fit(x, y)
        loss16 = net16.score_
    finally:
        env().set("matmul_precision", "float32")

    # same seed → same init; one bf16 step should track the fp32 loss to ~2%
    assert abs(loss16 - loss32) / max(abs(loss32), 1e-6) < 0.02


def test_amp_computation_graph(bf16_policy):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(3)
        .graph_builder()
        .add_inputs("in")
        .set_input_types(InputType.feed_forward(6))
        .add_layer("d1", DenseLayer(n_out=16, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "d1")
        .set_outputs("out")
        .build()
    )
    g = ComputationGraph(conf).init()
    rs = np.random.RandomState(2)
    x = rs.rand(5, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 5)]
    g.fit(DataSet(x, y))
    assert np.isfinite(g.score_)
    for layer_params in g.params_.values():
        for w in layer_params.values():
            assert w.dtype == jnp.float32
