"""Generic SequenceVectors SPI (VERDICT r4 missing #5; SURVEY §2.5 P1):
shared trainer, Word2Vec equivalence, sequence vectors, and a non-text
(DeepWalk random-walk) source — ref:
org.deeplearning4j.models.sequencevectors.SequenceVectors.
"""

import numpy as np

from deeplearning4j_tpu.nlp import (
    AbstractSequenceIterator,
    GraphWalkIterator,
    Sequence,
    SequenceElement,
    SequenceVectors,
    Word2Vec,
)

def _cluster_corpus(n=200, seed=1):
    """Two co-occurrence clusters (the proven test recipe from test_nlp)."""
    rs = np.random.RandomState(seed)
    a, b = ["cat", "dog", "pet"], ["car", "bus", "road"]
    return [" ".join(rs.choice(a if rs.rand() < 0.5 else b, size=6))
            for _ in range(n)]


CORPUS = _cluster_corpus()


class TestSharedTrainer:
    def test_equivalent_to_word2vec_on_text(self):
        """SequenceVectors over tokenized text == Word2Vec on the same
        corpus/seed (same fused engine underneath — the reference's class
        relationship, inverted into composition)."""
        it = AbstractSequenceIterator.from_token_lists(
            [s.split() for s in CORPUS])
        sv = (SequenceVectors.Builder().layer_size(16).window_size(3)
              .negative_sample(4).epochs(2).seed(7).iterate(it).build().fit())
        w2v = Word2Vec(layer_size=16, window=3, negative=4, epochs=2, seed=7,
                       subsampling=0.0)
        w2v.fit(CORPUS)
        for w in ("cat", "bus", "pet"):
            np.testing.assert_allclose(sv.get_element_vector(w),
                                       w2v.get_word_vector(w),
                                       rtol=1e-5, atol=1e-6)

    def test_semantic_neighbours(self):
        it = AbstractSequenceIterator.from_token_lists(
            [s.split() for s in CORPUS])
        sv = (SequenceVectors.Builder().layer_size(24).window_size(3)
              .negative_sample(4).learning_rate(0.1).epochs(10).seed(3)
              .iterate(it).build().fit())
        # in-cluster similarity beats cross-cluster
        assert sv.similarity("cat", "dog") > sv.similarity("cat", "car")
        assert sv.similarity("bus", "road") > sv.similarity("bus", "pet")

    def test_sequence_vectors_trained(self):
        seqs = [Sequence([SequenceElement(t) for t in s.split()],
                         SequenceElement(f"DOC_{i}"))
                for i, s in enumerate(CORPUS[:4])]
        sv = (SequenceVectors.Builder().layer_size(12).window_size(3)
              .negative_sample(3).epochs(3).seed(5)
              .train_sequences_representation(True)
              .iterate(AbstractSequenceIterator(seqs)).build().fit())
        v = sv.get_sequence_vector("DOC_0")
        assert v.shape == (12,) and np.all(np.isfinite(v))

    def test_cbow_algorithm_selection(self):
        it = AbstractSequenceIterator.from_token_lists(
            [s.split() for s in CORPUS])
        sv = (SequenceVectors.Builder().layer_size(8)
              .elements_learning_algorithm("CBOW").negative_sample(3)
              .epochs(1).iterate(it).build())
        assert sv.cbow is True
        sv.fit()
        assert sv.get_element_vector("cat").shape == (8,)


class TestGraphWalks:
    def test_deepwalk_clusters_nodes(self):
        """Two disjoint cliques: random-walk embeddings put same-clique
        nodes closer than cross-clique ones (the DeepWalk proof that the
        SPI is element-agnostic)."""
        adj = {"cat": ["dog", "pet"], "dog": ["cat", "pet"],
               "pet": ["cat", "dog"], "car": ["bus", "road"],
               "bus": ["car", "road"], "road": ["car", "bus"]}
        walks = GraphWalkIterator(adj, walk_length=6, walks_per_node=33, seed=1)
        sv = (SequenceVectors.Builder().layer_size(24).window_size(3)
              .negative_sample(4).learning_rate(0.1).epochs(10).seed(42)
              .iterate(walks).build().fit())
        d1 = sv.similarity("cat", "dog") - sv.similarity("cat", "car")
        d2 = sv.similarity("bus", "road") - sv.similarity("bus", "pet")
        assert d1 > 0.03 and d2 > 0.03, (d1, d2)

    def test_walk_iterator_is_restartable(self):
        walks = GraphWalkIterator({0: [1], 1: [0]}, walk_length=4,
                                  walks_per_node=2, seed=0)
        a = [s.labels() for s in walks]
        b = [s.labels() for s in walks]
        assert a == b and len(a) == 4
