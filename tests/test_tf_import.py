"""Graph-level TF import golden conformance (SURVEY §3.3 / §7.2#7).

A real HF TFBertModel is frozen to a GraphDef and imported node-by-node
into SameDiff; the imported graph's forward must match TF's own forward
(the live-golden pattern of test_keras_import). Also covers the generic
constant-folding of shape-arithmetic subgraphs and the allowlist error.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
transformers = pytest.importorskip("transformers")

from deeplearning4j_tpu.modelimport.tf_import import (  # noqa: E402
    TFGraphMapper,
    TFImportError,
)


def _freeze(fn, spec):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    conc = tf.function(fn).get_concrete_function(spec)
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), [t.name.split(":")[0] for t in frozen.outputs]


def test_small_graph_constant_folding():
    """Shape → StridedSlice → Pack → Reshape chains must fold to static
    shapes at import time (the XLA static-shape contract)."""
    def fn(x):
        s = tf.shape(x)
        b = s[0]
        flat = tf.reshape(x, tf.stack([b, -1]))
        return tf.nn.softmax(flat * 2.0 + 1.0)

    gd, outs = _freeze(fn, tf.TensorSpec([3, 4, 5], tf.float32))
    g = TFGraphMapper.import_graph(gd, outputs=outs)
    x = np.random.RandomState(0).randn(3, 4, 5).astype(np.float32)
    got = g.output({g.placeholders[0]: x})[outs[0]]
    want = fn(tf.constant(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_bert_frozen_graph_golden():
    cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=48, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    model = transformers.TFBertModel(cfg)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    _ = model(tf.constant(ids))  # build weights

    def fwd(input_ids):
        return model(input_ids).last_hidden_state

    gd, outs = _freeze(fwd, tf.TensorSpec([2, 16], tf.int32))
    want = fwd(tf.constant(ids)).numpy()

    g = TFGraphMapper.import_graph(gd, outputs=outs)
    got = g.output({g.placeholders[0]: ids})[outs[0]]
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_unsupported_op_raises_with_allowlist():
    def fn(x):
        return tf.signal.fft(tf.cast(x, tf.complex64))

    gd, outs = _freeze(fn, tf.TensorSpec([8], tf.float32))
    with pytest.raises(TFImportError, match="FFT"):
        TFGraphMapper.import_graph(gd, outputs=outs)
