"""Distributed layer tests on the 8-device CPU mesh (SURVEY §4.4/§4.6 #5 —
the TPU analog of local[N] Spark + DummyTransport)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (
    ParallelInference,
    ParallelTrainer,
    ParallelWrapper,
    ParameterAveragingTrainingMaster,
    build_mesh,
    compression,
)
from deeplearning4j_tpu.parallel.collectives import FakeCollectives, TransportError


def _mlp(seed=7, lr=0.05):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(lr)).list()
            .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 6).astype(np.float32)
    y = np.argmax(X[:, :3], axis=1)
    return X, np.eye(3, dtype=np.float32)[y]


def test_parallel_trainer_matches_single_device():
    """Sync DP over the mesh must equal the single-device step bitwise-close
    (same global batch, grads are a mean either way)."""
    X, Y = _data(32)
    ds = DataSet(X, Y)
    a, b = _mlp(), _mlp()
    a._fit_batch(ds)
    trainer = ParallelTrainer(b, mesh=build_mesh(data=8))
    trainer._fit_batch(ds)
    fa, fb = a.params().numpy(), b.params().numpy()
    np.testing.assert_allclose(fa, fb, atol=1e-5)


def test_parallel_trainer_remainder_batch():
    X, Y = _data(30)  # 30 % 8 != 0 → trim + remainder path
    net = _mlp()
    ParallelTrainer(net, mesh=build_mesh(data=8))._fit_batch(DataSet(X, Y))
    assert np.isfinite(net.score_)
    assert net.iteration == 2  # main shard + remainder


def test_parallel_wrapper_trains():
    X, Y = _data(64)
    net = _mlp()
    w = (ParallelWrapper.Builder(net).workers(8).prefetch_buffer(2).build())
    it = ListDataSetIterator([DataSet(X[i:i + 16], Y[i:i + 16]) for i in range(0, 64, 16)])
    s0 = None
    for _ in range(10):
        w.fit(it)
        s0 = s0 or net.score_
    assert net.score_ < s0


def test_parameter_averaging_master():
    X, Y = _data(64)
    net = _mlp()
    master = ParameterAveragingTrainingMaster(workers=4, averaging_frequency=2)
    it = ListDataSetIterator([DataSet(X[i:i + 8], Y[i:i + 8]) for i in range(0, 64, 8)])
    master.fit(net, it, epochs=3)
    assert np.isfinite(net.score_)


def test_parallel_inference_pads_and_trims():
    net = _mlp()
    pi = ParallelInference(net, batch_limit=16)
    X, _ = _data(5)  # 5 not divisible by 8 → padded to bucket, trimmed back
    out = pi.output(X)
    assert out.shape == (5, 3)
    ref = net.output(X).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_threshold_codec_roundtrip():
    rs = np.random.RandomState(3)
    g = rs.randn(1000).astype(np.float32) * 1e-3
    enc, residual = compression.threshold_residual(g, 1e-3)
    dec = compression.threshold_decode(enc, 1e-3)
    # decode+residual reconstructs g exactly
    np.testing.assert_allclose(dec + residual, g, atol=1e-7)
    # decoded entries only at |g| >= t, with sign preserved
    idx = np.nonzero(dec)[0]
    assert np.all(np.abs(g[idx]) >= 1e-3)
    assert np.all(np.sign(dec[idx]) == np.sign(g[idx]))


def test_bitmap_codec_roundtrip():
    rs = np.random.RandomState(4)
    g = rs.randn(257).astype(np.float32) * 2e-3
    packed, size = compression.bitmap_encode(g, 1e-3)
    dec = compression.bitmap_decode(packed, size, 1e-3)
    assert dec.shape == g.shape
    exp = np.where(g >= 1e-3, 1e-3, np.where(g <= -1e-3, -1e-3, 0.0)).astype(np.float32)
    np.testing.assert_allclose(dec, exp, atol=1e-8)


def test_fake_collectives_barrier_broadcast_and_failure():
    """DummyTransport-descendant: normal ops + injected failure aborts all."""
    router = FakeCollectives(world_size=3, timeout=5.0)
    results, errors = {}, {}

    def run(rank):
        w = router.worker(rank)
        try:
            w.barrier("start")
            results[rank] = w.broadcast("conf", {"lr": 0.1} if rank == 0 else None)
            g = w.gather("scores", rank * 1.0)
            if rank == 0:
                results["gathered"] = g
            if rank == 1:
                router.inject_failure(2)
            w.barrier("end")  # rank 2 is failed → everyone gets TransportError
        except TransportError as e:
            errors[rank] = e

    threads = [threading.Thread(target=run, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results[1] == {"lr": 0.1} and results[2] == {"lr": 0.1}
    assert results["gathered"] == [0.0, 1.0, 2.0]
    assert 0 in errors and 1 in errors  # live ranks observed the failure


def test_encoded_gradient_exchange_two_workers():
    """VERDICT r1 Weak #6: the threshold-codec DCN mode wired into an actual
    cross-worker exchange — encode→ship→decode→accumulate with residuals."""
    from deeplearning4j_tpu.parallel.compression import EncodedGradientsAccumulator

    router = FakeCollectives(world_size=2, timeout=5.0)
    rs = np.random.RandomState(0)
    g0 = rs.randn(64).astype(np.float32) * 1e-3
    g1 = rs.randn(64).astype(np.float32) * 1e-3
    thr = 1.5e-3
    updates, residuals = {}, {}

    def run(rank, grad):
        acc = EncodedGradientsAccumulator(router.worker(rank), threshold=thr)
        u1 = acc.exchange(grad)
        u2 = acc.exchange(grad)  # residual round: leftover mass ships now
        updates[rank] = (u1, u2)
        residuals[rank] = acc.residual

    threads = [threading.Thread(target=run, args=(r, g)) for r, g in [(0, g0), (1, g1)]]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # both workers computed the IDENTICAL summed sparse update each round
    np.testing.assert_array_equal(updates[0][0], updates[1][0])
    np.testing.assert_array_equal(updates[0][1], updates[1][1])
    # round 1 ships exactly the ±thr spikes of both workers' grads
    expected = np.zeros_like(g0)
    for g in (g0, g1):
        expected += np.where(np.abs(g) >= thr, np.sign(g) * thr, 0.0).astype(np.float32)
    np.testing.assert_allclose(updates[0][0], expected, atol=1e-7)
    # residual carries the un-shipped mass: grad+residual re-crosses the
    # threshold in round 2 for entries just below it
    assert np.any(updates[0][1] != 0.0)
    # conservation: shipped(u1 contribution) + shipped(u2) + residual ≈ 2*grad
    for rank, g in [(0, g0), (1, g1)]:
        own1 = np.where(np.abs(g) >= thr, np.sign(g) * thr, 0.0)
        carried = g - own1 + g
        own2 = np.where(np.abs(carried) >= thr, np.sign(carried) * thr, 0.0)
        np.testing.assert_allclose(own1 + own2 + residuals[rank], 2 * g, atol=1e-6)
