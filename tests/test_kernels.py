"""Pallas/ring attention parity vs the plain-XLA reference path.

SURVEY §4.6 #4: fast-path vs reference-path parity harness (the TPU analog of
the reference's ValidateCuDNN / CuDNNGradientChecks pattern).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.common import jax_compat
from deeplearning4j_tpu.kernels import flash_attention, mha_reference, ring_attention


def _qkv(shape=(2, 4, 256, 64)):
    k = jax.random.key(7)
    return [jax.random.normal(jax.random.fold_in(k, i), shape, jnp.float32) for i in range(3)]


def test_flash_matches_reference():
    q, k, v = _qkv()
    ref = mha_reference(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal_matches_reference():
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax_compat.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dot_product_attention_masked_parity():
    """The front door matches the dense reference under a padding mask on
    every backend — on TPU this is the masked-flash route (small T here
    stays dense per the >=128 cutoff; flash parity is tested directly)."""
    from deeplearning4j_tpu.kernels import dot_product_attention

    q, k, v = _qkv((2, 2, 64, 32))
    mask = jnp.concatenate([jnp.ones((2, 48)), jnp.zeros((2, 16))], axis=1)
    out = dot_product_attention(q, k, v, mask)
    ref = mha_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_key_padding_mask_matches_reference(causal):
    """VERDICT r4 weak #2: flash must handle BertIterator-style key padding
    masks natively instead of silently falling back to the O(T^2) path."""
    q, k, v = _qkv((2, 4, 256, 64))
    rs = np.random.RandomState(3)
    mask = jnp.asarray((rs.rand(2, 256) > 0.3).astype(np.float32))
    ref = mha_reference(q, k, v, mask, causal=causal)
    out = flash_attention(q, k, v, mask, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_masked_backward_matches_reference():
    q, k, v = _qkv((2, 2, 256, 32))
    rs = np.random.RandomState(9)
    mask = jnp.asarray((rs.rand(2, 256) > 0.25).astype(np.float32))

    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, mask, interpret=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, mask) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)


def test_flash_fully_masked_row_matches_reference():
    """A row with zero valid keys degrades to uniform attention in BOTH paths
    (large-finite-negative convention) — no NaNs forward or backward."""
    q, k, v = _qkv((1, 2, 128, 32))
    mask = (jnp.arange(128) < 64).astype(jnp.float32)[None, :]  # keys 0-63 valid
    ref = mha_reference(q, k, v, mask)
    out = flash_attention(q, k, v, mask, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    zero_mask = jnp.zeros((1, 128))
    out2 = flash_attention(q, k, v, zero_mask, interpret=True)
    ref2 = mha_reference(q, k, v, zero_mask)
    assert np.isfinite(np.asarray(out2)).all()
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=2e-5)
    g = jax.grad(lambda *a: jnp.sum(flash_attention(*a, zero_mask, interpret=True) ** 2),
                 argnums=(0,))(q, k, v)[0]
    assert np.isfinite(np.asarray(g)).all()


def test_flash_pad_shim_dead_rows_match_reference():
    """A row with ZERO live keys degrades to uniform softmax over the
    ORIGINAL keys even when the shim pads Tk (r5 review finding: the
    uniform fallback must not average the shim's zero-keys in)."""
    q, k, v = _qkv((2, 2, 200, 32))
    mask = jnp.ones((2, 200)).at[0, :].set(0.0)  # example 0 fully masked
    ref = mha_reference(q, k, v, mask)
    out = flash_attention(q, k, v, mask, interpret=True)  # pads 200 → 256
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # causal decode with Tq > Tk: leading queries attend zero keys
    q2, k2, v2 = _qkv((1, 2, 130, 32))
    k2, v2 = k2[:, :, :70], v2[:, :, :70]
    ref2 = mha_reference(q2, k2, v2, causal=True)
    out2 = flash_attention(q2, k2, v2, causal=True, block_q=64, block_k=64,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=2e-5)


@pytest.mark.parametrize("T", [100, 130])
def test_flash_pad_shim_odd_lengths(T):
    """Non-multiple-of-block sequence lengths round up and mask the padding
    out — forward AND backward parity with the dense reference."""
    q, k, v = _qkv((2, 2, T, 32))
    rs = np.random.RandomState(T)
    mask = jnp.asarray((rs.rand(2, T) > 0.2).astype(np.float32))
    for m in (None, mask):
        ref = mha_reference(q, k, v, m)
        out = flash_attention(q, k, v, m, block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, mask, block_q=64,
                                                     block_k=64, interpret=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, mask) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)


def test_flash_segment_ids_block_diagonal():
    """segment_ids restrict attention to equal ids (packed sequences)."""
    q, k, v = _qkv((2, 2, 128, 32))
    segs = jnp.asarray(np.repeat([[0, 1, 2, 3]], 32, axis=1).reshape(1, 128)
                       .repeat(2, axis=0))
    dense = (segs[:, :, None] == segs[:, None, :])[:, None].astype(jnp.float32)
    ref = mha_reference(q, k, v, dense)
    out = flash_attention(q, k, v, segment_ids=segs, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # segments compose with a padding mask: padded keys drop out of their segment
    mask = jnp.ones((2, 128)).at[:, 120:].set(0.0)
    ref2 = mha_reference(q, k, v, dense * mask[:, None, None, :])
    out2 = flash_attention(q, k, v, mask, segment_ids=segs, interpret=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2), atol=2e-5)


def test_flash_attention_backward_parity():
    """flash_attention is differentiable (custom_vjp): grads match the
    reference-path grads. Guards the BERT train step's auto→flash path."""
    import numpy as np

    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, 2, 128, 16), jnp.float32) for _ in range(3))

    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_backward_matches_dense_oracle(causal, masked):
    """Blockwise Pallas backward == dense-reconstruction oracle, multi-block."""
    from deeplearning4j_tpu.kernels.attention import (
        _flash_bwd,
        _flash_bwd_dense,
        _flash_fwd,
    )

    q, k, v = _qkv((2, 2, 256, 32))
    scale = 1.0 / np.sqrt(32)
    qseg = kseg = None
    if masked:
        rs = np.random.RandomState(1)
        qseg = jnp.zeros((2, 256), jnp.int32)
        kseg = jnp.asarray(np.where(rs.rand(2, 256) > 0.3, 0, -1), jnp.int32)
    do = jax.random.normal(jax.random.key(11), q.shape, jnp.float32)
    out, res = _flash_fwd(q, k, v, qseg, kseg, causal, scale, 128, 128, True, 0)
    dq, dk, dv, _, _ = _flash_bwd(causal, scale, 128, 128, True, 0, res, do)
    dq0, dk0, dv0 = _flash_bwd_dense(causal, scale, res, do)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq0), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk0), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv0), atol=3e-5)


def test_flash_backward_rectangular_decode():
    """Tq != Tk (decode-with-prefix): causal offset aligns to the key end."""
    kk = jax.random.key(3)
    q = jax.random.normal(jax.random.fold_in(kk, 0), (1, 2, 64, 32), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(kk, 1), (1, 2, 256, 32), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(kk, 2), (1, 2, 256, 32), jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True, block_q=64,
                                                     interpret=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    """All-to-all sequence parallelism == full attention (SURVEY §2.10 SP)."""
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((2, 4, 256, 32))
    ref = mha_reference(q, k, v, causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax_compat.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_respects_key_mask():
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((2, 4, 64, 16))
    rs = np.random.RandomState(5)
    mask = jnp.asarray((rs.rand(2, 64) > 0.3).astype(np.float32))
    ref = mha_reference(q, k, v, mask)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax_compat.shard_map(
        lambda a, b, c, m: ulysses_attention(a, b, c, axis_name="sp", key_mask=m),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_heads_divisibility_error():
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((1, 3, 64, 16))  # 3 heads, 4 devices
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax_compat.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp"),
        mesh=mesh, in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    with pytest.raises(ValueError, match="divisible"):
        f(q, k, v)


def test_flash_long_t_auto_blocks_match_reference():
    """T >= 4096 auto-selects (512, 1024) blocks (the measured long-T sweet
    spot); numerics must match the dense reference under a mask."""
    q, k, v = _qkv((1, 2, 4096, 16))
    mask = jnp.ones((1, 4096)).at[:, 3700:].set(0.0)
    out = flash_attention(q, k, v, mask, interpret=True)
    ref = mha_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
