"""Pallas/ring attention parity vs the plain-XLA reference path.

SURVEY §4.6 #4: fast-path vs reference-path parity harness (the TPU analog of
the reference's ValidateCuDNN / CuDNNGradientChecks pattern).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.kernels import flash_attention, mha_reference, ring_attention


def _qkv(shape=(2, 4, 256, 64)):
    k = jax.random.key(7)
    return [jax.random.normal(jax.random.fold_in(k, i), shape, jnp.float32) for i in range(3)]


def test_flash_matches_reference():
    q, k, v = _qkv()
    ref = mha_reference(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal_matches_reference():
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_masked_fallback():
    """dot_product_attention with a padding mask routes to the reference path."""
    from deeplearning4j_tpu.kernels import dot_product_attention

    q, k, v = _qkv((2, 2, 64, 32))
    mask = jnp.concatenate([jnp.ones((2, 48)), jnp.zeros((2, 16))], axis=1)
    out = dot_product_attention(q, k, v, mask)
    ref = mha_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_flash_attention_backward_parity():
    """flash_attention is differentiable (custom_vjp): grads match the
    reference-path grads. Guards the BERT train step's auto→flash path."""
    import numpy as np

    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(2, 2, 128, 16), jnp.float32) for _ in range(3))

    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_backward_matches_dense_oracle(causal):
    """Blockwise Pallas backward == dense-reconstruction oracle, multi-block."""
    from deeplearning4j_tpu.kernels.attention import (
        _flash_bwd,
        _flash_bwd_dense,
        _flash_fwd,
    )

    q, k, v = _qkv((2, 2, 256, 32))
    do = jax.random.normal(jax.random.key(11), q.shape, jnp.float32)
    out, res = _flash_fwd(q, k, v, causal, None, 128, 128, True)
    dq, dk, dv = _flash_bwd(causal, None, 128, 128, True, res, do)
    dq0, dk0, dv0 = _flash_bwd_dense(causal, None, res, do)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq0), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk0), atol=3e-5)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv0), atol=3e-5)


def test_flash_backward_rectangular_decode():
    """Tq != Tk (decode-with-prefix): causal offset aligns to the key end."""
    kk = jax.random.key(3)
    q = jax.random.normal(jax.random.fold_in(kk, 0), (1, 2, 64, 32), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(kk, 1), (1, 2, 256, 32), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(kk, 2), (1, 2, 256, 32), jnp.float32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True, block_q=64,
                                                     interpret=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    """All-to-all sequence parallelism == full attention (SURVEY §2.10 SP)."""
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((2, 4, 256, 32))
    ref = mha_reference(q, k, v, causal=causal)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_respects_key_mask():
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((2, 4, 64, 16))
    rs = np.random.RandomState(5)
    mask = jnp.asarray((rs.rand(2, 64) > 0.3).astype(np.float32))
    ref = mha_reference(q, k, v, mask)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax.shard_map(
        lambda a, b, c, m: ulysses_attention(a, b, c, axis_name="sp", key_mask=m),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None),
    )
    out = f(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_heads_divisibility_error():
    from deeplearning4j_tpu.kernels import ulysses_attention

    q, k, v = _qkv((1, 3, 64, 16))  # 3 heads, 4 devices
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    f = jax.shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp"),
        mesh=mesh, in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    with pytest.raises(ValueError, match="divisible"):
        f(q, k, v)
