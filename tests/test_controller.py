"""Self-driving model lifecycle (ISSUE 18 tentpole).

The :class:`FleetController` watches a checkpoint lineage and drives every
newly committed generation through integrity → eval → canary gates,
promoting on sustained-clear and rolling back on any failure. Covers: the
poisoned-candidate matrix (bit-flipped → integrity gate, loss-spiked →
eval gate, latency-injected → canary SLO gate — each rejected at the
EARLIEST gate that can catch it, with the old fleet untouched), durable
SIGKILL/restart resume to the same terminal verdict, bounded gate timeouts,
transient-error retry, the decision-event AST lint (with a planted-offender
self-test), the eval ``to_metrics`` hook, and the enriched swap-rejection
payload.
"""

import ast
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.deploy import GATE_CHAIN, FleetController
from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.monitoring.flight import (EVENT_KINDS, FlightRecorder,
                                                  set_flight_recorder)
from deeplearning4j_tpu.serde.checkpoint import (_array_crc, _gen_name,
                                                 _self_checksummed)
from deeplearning4j_tpu.serving import ServingPool

ROOT = pathlib.Path(__file__).resolve().parent.parent
_POOL_WORKERS = str(pathlib.Path(__file__).resolve().parent
                    / "pool_workers.py")
_CTRL_WORKERS = str(pathlib.Path(__file__).resolve().parent
                    / "controller_workers.py")
_GANG_WORKERS = str(pathlib.Path(__file__).resolve().parent
                    / "mp_workers.py")


# ------------------------------------------------------------- helpers


def _make_gen(lineage, it, corrupt=False, scale=1.0):
    """Hand-roll one COMMITTED generation (the test_pool idiom). ``scale``
    multiplies the weights — a structurally perfect artifact with ruined
    numbers, the loss-spike poison's signature. ``corrupt`` flips a byte in
    the shard AFTER the commit — latent bit-rot."""
    gen = _gen_name(it)
    gendir = os.path.join(str(lineage), gen)
    os.makedirs(gendir)
    w = (np.linspace(-0.5, 0.5, 64).astype(np.float32) * scale)
    blob = {"__save_id__": np.asarray(it, np.int64),
            "params/0/W|0": w,
            "params/0/W|0|idx": np.asarray([[0, 64]], np.int64),
            "params/0/W|0|shape": np.asarray([64], np.int64)}
    with open(os.path.join(gendir, "shard_0.npz"), "wb") as f:
        np.savez(f, **blob)
    manifest = _self_checksummed({
        "save_id": it, "proc": 0, "shard": "shard_0.npz",
        "process_count": 1, "layout": None,
        "entries": {k: _array_crc(v) for k, v in blob.items()},
        "nbytes": 0})
    with open(os.path.join(gendir, "manifest_0.json"), "w") as f:
        f.write(json.dumps(manifest))
    with open(os.path.join(gendir, "train_state.json"), "w") as f:
        f.write(json.dumps(_self_checksummed(
            {"iteration": it, "epoch": 0, "score": None,
             "process_count": 1, "generation": gen})))
    with open(os.path.join(gendir, "COMMIT"), "w") as f:
        f.write("{}")
    with open(os.path.join(str(lineage), "LATEST"), "w") as f:
        f.write(gen + "\n")
    if corrupt:
        shard = os.path.join(gendir, "shard_0.npz")
        raw = open(shard, "rb").read()
        off = raw.index(w.tobytes()) + 8
        with open(shard, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    return gendir


def _weight_eval(gendir):
    """Weight-reading eval stub: a loss-spiked generation's blown-up
    parameters score near zero, a healthy one near 0.9."""
    with np.load(os.path.join(gendir, "shard_0.npz")) as z:
        w = z["params/0/W|0"]
    return {"accuracy": 0.9 if float(np.abs(w).mean()) < 1.0 else 0.1}


def _counter_values(reg, name):
    m = reg.get(name)
    if m is None:
        return {}
    return {tuple(s["labels"].values()): s["value"]
            for s in m.snapshot()["series"]}


def _controller(tmp_path, **kw):
    kw.setdefault("workdir", str(tmp_path / "deploy"))
    kw.setdefault("eval_fn", _weight_eval)
    kw.setdefault("eval_thresholds", {"accuracy": 0.8})
    kw.setdefault("retries", 0)
    kw.setdefault("retry_backoff_s", 0.05)
    kw.setdefault("registry", MetricsRegistry())
    return FleetController(str(tmp_path / "ck"), **kw)


@pytest.fixture
def lineage(tmp_path):
    d = tmp_path / "ck" / "latest"
    d.mkdir(parents=True)
    return d


# ------------------------------------------- gate chain without a pool


def test_healthy_candidate_promotes_and_survives_restart(tmp_path, lineage):
    """A committed healthy generation walks integrity → eval and promotes
    (no pool: promotion moves the durable baseline); a second controller on
    the same workdir re-derives nothing — terminal verdicts are durable."""
    _make_gen(lineage, 2)
    c = _controller(tmp_path)
    try:
        out = c.run_once()
        assert [e["status"] for e in out] == ["promoted"]
        assert c.state["promoted"]["generation"] == _gen_name(2)
        assert c.state["promoted"]["metrics"]["accuracy"] == 0.9
        kinds = [e["kind"] for e in c._own_recorder.events()]
        assert kinds == ["deploy_candidate", "deploy_gate", "deploy_gate",
                         "deploy_promote"]
        reg = c.registry
        assert _counter_values(reg, "tdl_deploy_promotions_total") == {(): 1}
        assert reg.get("tdl_deploy_promoted_generation").value == 2.0
        audit = json.load(open(c.audit_path))
        assert audit["promoted"]["generation"] == _gen_name(2)
        gates = [v["gate"] for v in audit["candidates"][0]["verdicts"]]
        assert gates == ["integrity", "eval"]
    finally:
        c.close()

    c2 = _controller(tmp_path, registry=MetricsRegistry())
    try:
        assert c2.run_once() == []  # nothing new, nothing re-judged
        assert c2.state["promoted"]["generation"] == _gen_name(2)
    finally:
        c2.close()


def test_bit_flipped_candidate_rejected_at_integrity_gate(tmp_path, lineage):
    """Poison matrix 1: a bit-flipped generation dies at the FIRST gate —
    integrity — for the price of a read. The eval gate never runs, the
    promoted baseline is untouched, and the audit names the evidence."""
    _make_gen(lineage, 2)
    _make_gen(lineage, 4, corrupt=True)
    seen = []
    c = _controller(tmp_path, eval_fn=lambda d: seen.append(d) or
                    _weight_eval(d))
    try:
        c.run_once()
        cand = c.state["candidates"][_gen_name(4)]
        assert cand["status"] == "rejected"
        assert cand["rejected_by"] == {"gate": "integrity",
                                       "reason": "shard_crc"}
        assert [v["gate"] for v in cand["verdicts"]] == ["integrity"]
        assert seen == [c.state["candidates"][_gen_name(2)]["dir"]]
        assert c.state["promoted"]["generation"] == _gen_name(2)
        rb = [e for e in c._own_recorder.events()
              if e["kind"] == "deploy_rollback"]
        assert len(rb) == 1 and rb[0]["gate"] == "integrity"
        assert rb[0]["reason"] == "shard_crc"
        assert _counter_values(c.registry, "tdl_deploy_rollbacks_total") \
            == {("integrity",): 1}
        audit = json.load(open(c.audit_path))
        bad = [x for x in audit["candidates"]
               if x["generation"] == _gen_name(4)][0]
        assert bad["verdicts"][0]["evidence"]["verify"]["reason"] \
            == "shard_crc"
    finally:
        c.close()


def test_loss_spiked_candidate_rejected_at_eval_gate(tmp_path, lineage):
    """Poison matrix 2: a loss-spiked generation is structurally PERFECT —
    integrity passes — and only the offline eval gate can reject it
    (threshold floor AND regression band vs the promoted baseline). The
    judged numbers land on /metrics under the model label."""
    _make_gen(lineage, 2)
    _make_gen(lineage, 4, scale=40.0)
    c = _controller(tmp_path, regression_band=0.05)
    try:
        c.run_once()
        cand = c.state["candidates"][_gen_name(4)]
        assert cand["status"] == "rejected"
        assert cand["rejected_by"]["gate"] == "eval"
        assert "accuracy" in cand["rejected_by"]["reason"]
        # integrity PASSED first: the eval gate is the earliest catcher
        assert [(v["gate"], v["ok"]) for v in cand["verdicts"]] \
            == [("integrity", True), ("eval", False)]
        assert c.state["promoted"]["generation"] == _gen_name(2)
        ev = cand["verdicts"][1]["evidence"]
        assert ev["metrics"]["accuracy"] == 0.1
        assert ev["baseline"]["accuracy"] == 0.9
        acc = _counter_values(c.registry, "tdl_eval_accuracy")
        assert acc == {(_gen_name(2),): 0.9, (_gen_name(4),): 0.1}
    finally:
        c.close()


def test_quarantined_candidate_honors_the_evidence(tmp_path, lineage):
    """A generation the restore side already quarantined (renamed
    ``*.corrupt``) fails integrity with reason=quarantined — the gate
    honors the condemnation instead of re-blessing moved bytes."""
    gendir = _make_gen(lineage, 4)
    c = _controller(tmp_path)
    try:
        os.rename(gendir, gendir + ".corrupt-shard_crc")
        entry = {"generation": _gen_name(4), "iteration": 4, "dir": gendir,
                 "verdicts": []}
        v = c._gate_integrity(entry, {"dir": str(lineage), "quarantined":
                                      [_gen_name(4) + ".corrupt-shard_crc"]})
        assert not v["ok"] and v["reason"] == "quarantined"
        assert v["evidence"]["quarantined"] \
            == [_gen_name(4) + ".corrupt-shard_crc"]
    finally:
        c.close()


def test_wedged_gate_times_out_into_rollback(tmp_path, lineage):
    """Robustness: a gate that never returns hits ``gate_timeout_s`` and
    becomes a failing verdict (reason=timeout) — the controller never
    hangs, the candidate rolls back."""
    _make_gen(lineage, 2)
    c = _controller(tmp_path, eval_fn=lambda d: time.sleep(60),
                    gate_timeout_s=0.4)
    try:
        c.run_once()
        cand = c.state["candidates"][_gen_name(2)]
        assert cand["status"] == "rejected"
        assert cand["rejected_by"] == {"gate": "eval", "reason": "timeout"}
    finally:
        c.close()


def test_transient_gate_errors_retry_before_counting(tmp_path, lineage):
    """Robustness: exceptions escaping a gate fn are transient — retried
    with backoff. Two flaky failures then success promotes; with retries
    exhausted the error becomes the verdict."""
    _make_gen(lineage, 2)
    calls = []

    def flaky(gendir):
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient fs hiccup")
        return _weight_eval(gendir)

    c = _controller(tmp_path, eval_fn=flaky, retries=2)
    try:
        c.run_once()
        cand = c.state["candidates"][_gen_name(2)]
        assert cand["status"] == "promoted" and len(calls) == 3
        ev = [v for v in cand["verdicts"] if v["gate"] == "eval"][0]
        assert ev["evidence"]["retries"] == 2
    finally:
        c.close()

    def always(gendir):
        raise OSError("disk on fire")

    _make_gen(lineage, 4)
    c2 = _controller(tmp_path, workdir=str(tmp_path / "deploy2"),
                     eval_fn=always, retries=1)
    try:
        c2.run_once()
        cand = c2.state["candidates"][_gen_name(4)]
        assert cand["status"] == "rejected"
        assert cand["rejected_by"] == {"gate": "eval",
                                       "reason": "error:OSError"}
        ev = cand["verdicts"][-1]["evidence"]
        assert ev["attempts"] == 2
    finally:
        c2.close()


# --------------------------------------------------- SIGKILL → resume


def test_sigkilled_controller_resumes_to_same_verdict(tmp_path, lineage):
    """Acceptance: a controller SIGKILLed mid-gate restarts on the same
    workdir and reaches the same terminal verdict. Gate verdicts recorded
    before the kill (integrity PASS) are durable and NOT re-run; the
    candidate resumes at the exact gate it died in."""
    _make_gen(lineage, 4)
    cfg = {"ckpt_dir": str(tmp_path / "ck"),
           "workdir": str(tmp_path / "deploy"),
           "gates": ["integrity", "eval"],
           "eval_target": f"{_CTRL_WORKERS}:eval_sleepy",
           "eval_thresholds": {"accuracy": 0.8},
           "retries": 0}
    cfg_path = tmp_path / "controller.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu", TDL_EVAL_SLEEP="120",
               TDL_EVAL_ACC="0.9")
    cmd = [sys.executable, "-m", "deeplearning4j_tpu.deploy.controller",
           str(cfg_path), "--once"]
    p1 = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE)
    state_path = tmp_path / "deploy" / "controller_state.json"
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                st = json.loads(state_path.read_text())
                cand = st["candidates"][_gen_name(4)]
                if cand["status"] == "in_gate" and cand["gate"] == "eval":
                    break  # integrity verdict durable, eval gate entered
            except (OSError, ValueError, KeyError):
                pass
            time.sleep(0.05)
        else:
            pytest.fail("controller never reached the eval gate")
        assert [v["gate"] for v in cand["verdicts"]] == ["integrity"]
    finally:
        p1.send_signal(signal.SIGKILL)
        p1.wait(timeout=30)

    env2 = dict(os.environ, JAX_PLATFORMS="cpu", TDL_EVAL_ACC="0.9")
    p2 = subprocess.run(cmd, env=env2, capture_output=True, text=True,
                        timeout=300)
    assert p2.returncode == 0, p2.stderr[-3000:]
    summary = json.loads(p2.stdout.strip().splitlines()[-1])
    assert summary["candidates"] == {_gen_name(4): "promoted"}
    st = json.loads(state_path.read_text())
    cand = st["candidates"][_gen_name(4)]
    assert cand["resumed"] is True  # the audit says this verdict survived a death
    # integrity ran ONCE (before the kill); only eval re-ran after resume
    assert [v["gate"] for v in cand["verdicts"]] == ["integrity", "eval"]
    audit = json.loads((tmp_path / "deploy" / "audit.json").read_text())
    assert audit["promoted"]["generation"] == _gen_name(4)


# ------------------------------------------------------- canary gates


def _stub_pool(tmp_path, reg, **kw):
    kw.setdefault("replicas", 1)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    return ServingPool(f"{_POOL_WORKERS}:stub_server",
                       workdir=str(tmp_path / "pool"), registry=reg, **kw)


def test_canary_gate_rejects_latency_injected_candidate(tmp_path, lineage):
    """Poison matrix 3: a candidate that only misbehaves under LIVE traffic
    (latency injected into inference whenever TDL_MODEL_CKPT names it)
    passes integrity and eval, and is caught by the canary SLO gate — the
    paired replay fires the latency/burn rules for consecutive windows. The
    canary was router-invisible throughout and the old fleet still serves."""
    from deeplearning4j_tpu.serving.loadgen import TraceSpec

    gendir = _make_gen(tmp_path / "ck" / "latest", 4)
    (lineage / "LATEST").write_text(_gen_name(4) + "\n")
    reg = MetricsRegistry()
    pool = _stub_pool(tmp_path, reg, extra_env={
        "TDL_FAULT_SPEC":
            f"latency_inject@value=0.25,model={_gen_name(4)}"}).start()
    c = None
    try:
        assert pool.wait_ready(60.0)
        c = _controller(
            tmp_path, pool=pool, registry=reg,
            trace=TraceSpec(duration_s=1.5, base_rate=24.0, seed=18),
            slo_threshold_ms=120.0, burn_window_s=0.5,
            canary_ready_timeout=60.0)
        assert c.gates == GATE_CHAIN
        c.run_once()
        cand = c.state["candidates"][_gen_name(4)]
        assert cand["status"] == "rejected", cand
        assert cand["rejected_by"]["gate"] == "canary"
        assert cand["rejected_by"]["reason"].startswith("slo:")
        # caught at the EARLIEST gate that can see it: the first two passed
        assert [(v["gate"], v["ok"]) for v in cand["verdicts"]] == \
            [("integrity", True), ("eval", True), ("canary", False)]
        fired = [v for v in cand["verdicts"]
                 if v["gate"] == "canary"][0]["evidence"]["fired"]
        assert fired and all(f["rule"].startswith("canary_") for f in fired)
        # old fleet untouched: no canary rows left, pool still serves
        rows = pool.describe()["replicas"]
        assert all(not r["canary"] for r in rows)
        assert all(r["model"] is None for r in rows)  # never swapped
        assert pool.wait_ready(30.0)
        assert _counter_values(reg, "tdl_deploy_rollbacks_total") \
            == {("canary",): 1}
        assert gendir in json.load(open(c.audit_path))["candidates"][0]["dir"]
    finally:
        if c is not None:
            c.close()
        pool.stop()


def test_clean_canary_promotes_and_completes_the_swap(tmp_path, lineage):
    """The promote leg: a healthy candidate clears the canary window and
    the controller completes the rolling swap — every replica (and the
    pool's default overrides, so future scale-ups too) carries the
    promoted generation."""
    from deeplearning4j_tpu.serving.loadgen import TraceSpec

    gendir = _make_gen(tmp_path / "ck" / "latest", 6)
    reg = MetricsRegistry()
    pool = _stub_pool(tmp_path, reg).start()
    c = None
    try:
        assert pool.wait_ready(60.0)
        c = _controller(
            tmp_path, pool=pool, registry=reg,
            trace=TraceSpec(duration_s=1.5, base_rate=30.0, seed=18),
            slo_threshold_ms=1000.0, burn_window_s=0.5)
        c.run_once()
        cand = c.state["candidates"][_gen_name(6)]
        assert cand["status"] == "promoted", cand
        gates = [(v["gate"], v["ok"]) for v in cand["verdicts"]]
        assert gates == [("integrity", True), ("eval", True),
                         ("canary", True), ("promote", True)]
        assert c.state["promoted"]["generation"] == _gen_name(6)
        rows = pool.describe()["replicas"]
        assert rows and all(r["model"] == gendir for r in rows)
        assert all(not r["canary"] for r in rows)
        assert reg.get("tdl_deploy_promoted_generation").value == 6.0
        # canary SLO gauges were exercised by the paired judgement
        assert reg.get("tdl_deploy_canary_availability") is not None
    finally:
        if c is not None:
            c.close()
        pool.stop()


# ------------------------------------------- satellites: eval + swap


def test_evaluation_to_metrics_sets_model_gauges():
    """Satellite: ``Evaluation.to_metrics`` returns the judged numbers AND
    lands them on the registry under the model label — the eval gate and
    the /metrics scrape cannot disagree."""
    from deeplearning4j_tpu.eval import Evaluation, RegressionEvaluation

    reg = MetricsRegistry()
    ev = Evaluation()
    y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    p = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]  # 3/4 right
    ev.eval(y, p)
    m = ev.to_metrics(reg, model="gen-x")
    assert m["accuracy"] == pytest.approx(0.75)
    assert m["score"] == pytest.approx(0.75)
    assert 0.0 < m["f1"] <= 1.0
    assert _counter_values(reg, "tdl_eval_accuracy") \
        == {("gen-x",): pytest.approx(0.75)}
    assert ("gen-x",) in _counter_values(reg, "tdl_eval_f1")

    rev = RegressionEvaluation()
    rev.eval(np.asarray([[1.0], [2.0], [3.0]]),
             np.asarray([[1.1], [1.9], [3.2]]))
    rm = rev.to_metrics(reg, model="gen-r")
    assert rm["score"] == pytest.approx(rev.r_squared(0))
    assert ("gen-r",) in _counter_values(reg, "tdl_eval_score")


def test_swap_rejection_names_the_full_verdict(tmp_path):
    """Satellite: ``swap_model`` pre-flight rejection surfaces the verify
    verdict — reason, generation, iteration, format — in BOTH the raised
    error and the ``pool_swap_rejected`` flight payload, not just "no"."""
    lineage = tmp_path / "ck" / "latest"
    lineage.mkdir(parents=True)
    _make_gen(lineage, 3, corrupt=True)
    rec = FlightRecorder(proc="test", interval=0.0)
    set_flight_recorder(rec)
    try:
        pool = _stub_pool(tmp_path, MetricsRegistry())  # never started
        with pytest.raises(ValueError) as ei:
            pool.swap_model(str(tmp_path / "ck"))
        msg = str(ei.value)
        assert "reason=shard_crc" in msg
        assert f"generation={_gen_name(3)}" in msg
        assert "iteration=3" in msg
        ev = [e for e in rec.events() if e["kind"] == "pool_swap_rejected"]
        assert len(ev) == 1
        assert ev[0]["reason"] == "shard_crc"
        assert ev[0]["generation"] == _gen_name(3)
        assert ev[0]["iteration"] == 3
        assert ev[0]["format"] in ("lineage", "generation")
        assert ev[0]["verify_seconds"] >= 0
    finally:
        set_flight_recorder(None)


# -------------------------------------------------- fault vocabulary


def test_loss_spike_fault_grammar_and_poison_scale(monkeypatch):
    """``loss_spike`` parses, fires only at its iteration, and returns the
    multiplicative scale the trainer applies to its parameter tree."""
    from deeplearning4j_tpu.common import faults

    fs = faults.parse_fault_spec("loss_spike@iter=4,scale=40")
    assert fs[0].kind == "loss_spike" and fs[0].iteration == 4
    monkeypatch.setenv("TDL_FAULT_SPEC", "loss_spike@iter=4,scale=25")
    monkeypatch.setenv("TDL_GANG_RESTART_COUNT", "0")
    assert faults.poison_scale("train_step", 3) is None
    assert faults.poison_scale("train_step", 4) == 25.0
    assert faults.poison_scale("train_step", 5) is None
    # one-shot: a restarted incarnation does not re-spike
    monkeypatch.setenv("TDL_GANG_RESTART_COUNT", "1")
    assert faults.poison_scale("train_step", 4) is None
    monkeypatch.delenv("TDL_FAULT_SPEC")
    assert faults.poison_scale("train_step", 4) is None


def test_latency_inject_fires_only_for_the_named_model(monkeypatch):
    """``latency_inject`` sleeps inside inference batches ONLY in replicas
    whose TDL_MODEL_CKPT names the poisoned generation — the mechanism that
    makes a canary slow while the baseline fleet stays fast."""
    from deeplearning4j_tpu.common import faults

    monkeypatch.setenv("TDL_FAULT_SPEC",
                       "latency_inject@value=0.15,model=gen-00000008")
    monkeypatch.delenv("TDL_MODEL_CKPT", raising=False)
    t0 = time.perf_counter()
    faults.fault_point("infer")
    assert time.perf_counter() - t0 < 0.1  # wrong arm: no sleep
    monkeypatch.setenv("TDL_MODEL_CKPT", "/ck/latest/gen-00000008")
    t0 = time.perf_counter()
    faults.fault_point("infer")
    assert time.perf_counter() - t0 >= 0.15


# ---------------------------------------------------- decision lint


#: every decision method and the flight event it must record before any
#: non-delegated return path
_DECISION_EVENTS = {"_announce_candidate": "deploy_candidate",
                    "_record_verdict": "deploy_gate",
                    "_promote": "deploy_promote",
                    "_rollback": "deploy_rollback"}


def _record_kind_literals(node):
    out = []
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "record"
                and sub.args
                and isinstance(sub.args[0], ast.Constant)
                and isinstance(sub.args[0].value, str)):
            out.append((sub.args[0].value, sub.lineno))
    return out


def _unflighted_decision_paths(tree):
    """Return paths in controller decision methods that could complete
    without the decision's flight event: [(method, lineno, why)]. A return
    that DELEGATES to another decision method (``return self._rollback(...)``)
    is flighted transitively and exempt."""
    offenders = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name in _DECISION_EVENTS):
            continue
        want = _DECISION_EVENTS[node.name]
        record_lines = [ln for kind, ln in _record_kind_literals(node)
                        if kind == want]
        if not record_lines:
            offenders.append((node.name, node.lineno, f"never records "
                              f"{want!r}"))
            continue
        first = min(record_lines)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Return) or sub.lineno >= first:
                continue
            v = sub.value
            if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                    and v.func.attr in _DECISION_EVENTS):
                continue
            offenders.append((node.name, sub.lineno,
                              f"returns before recording {want!r}"))
    return offenders


def test_controller_decisions_are_flighted():
    """CI lint (satellite): every promote / rollback / gate-verdict /
    candidate decision path in controller.py records its flight event (from
    the declared kind set) before returning — an unattended controller whose
    decisions don't reach the audit trail is a silent operator."""
    src = (ROOT / "deeplearning4j_tpu" / "deploy" / "controller.py")
    tree = ast.parse(src.read_text(), filename=str(src))
    assert _unflighted_decision_paths(tree) == []
    # and every kind used is registered in the flight schema
    for kind, _ in _record_kind_literals(tree):
        assert kind in EVENT_KINDS, kind
    found = {n.name for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef)}
    assert set(_DECISION_EVENTS) <= found  # the lint actually saw them


def test_decision_lint_catches_a_planted_offender():
    """The lint must bite: a decision method with an early bare return (or
    no record at all) is flagged; the delegated-return idiom passes."""
    planted = ast.parse(
        "class X:\n"
        "    def _promote(self, entry):\n"
        "        if entry is None:\n"
        "            return None\n"  # escapes without the event: offender
        "        flight.record('deploy_promote', generation='g')\n"
        "        return entry\n"
        "    def _rollback(self, entry, verdict):\n"
        "        return None\n"  # no event at all: offender
        "    def _record_verdict(self, entry, verdict):\n"
        "        if verdict is None:\n"
        "            return self._rollback(entry, verdict)\n"  # delegated: ok
        "        flight.record('deploy_gate', gate='eval')\n"
        "        return verdict\n")
    bad = _unflighted_decision_paths(planted)
    assert [(m, why.split(" ")[0]) for m, _, why in bad] \
        == [("_promote", "returns"), ("_rollback", "never")]


# ------------------------------------------------------ e2e (slow)


@pytest.mark.slow
def test_e2e_chaos_train_gate_promote_unattended(tmp_path):
    """ISSUE 18 acceptance: train a model under injected chaos (a rank
    crash mid-run), let the lineage commit generations — three of them
    poisoned (bit-flipped, latency-injected, loss-spiked) — then run the
    controller unattended against a live pool under replayed traffic.
    Each poison must be rejected at the EARLIEST gate that can catch it,
    with gate/reason/evidence in audit.json mirrored as deploy_rollback
    flight events, one healthy generation must auto-promote through the
    canary, and only 200/429 ever escape the pool."""
    from deeplearning4j_tpu.parallel.supervisor import GangSupervisor
    from deeplearning4j_tpu.serving.loadgen import TraceSpec, replay
    from tests.controller_workers import eval_candidate

    ckroot = tmp_path / "ck"
    ckroot.mkdir()
    env = {"TDL_MP_CKPT": str(ckroot), "TDL_MP_STEPS": "12",
           "TDL_MP_CKPT_EVERY": "3",
           "TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MATMUL_PRECISION": "float32",
           # chaos: rank 1 dies at iter 7 (restart resumes from gen 6);
           # the restarted incarnation hits a loss spike at iter 11, so
           # gen-12 commits structurally perfect but ruined weights
           "TDL_FAULT_SPEC": "crash@iter=7,rank=1;"
                             "loss_spike@iter=11,scale=60,restart=1"}
    sup = GangSupervisor(f"{_CTRL_WORKERS}:lifecycle_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, backoff_base=0.1,
                         kill_grace=1.0, max_restarts=3,
                         registry=MetricsRegistry())
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1  # the crash chaos really happened

    lineage = ckroot / "latest"
    gens = sorted(d for d in os.listdir(lineage) if d.startswith("gen-")
                  and not d.endswith("corrupt"))
    # gens at iterations 3, 6, 9, 12 (every=3 over 12 steps)
    assert [int(g.split("-")[1].rstrip("abcdefghijklmnopqrstuvwxyz"))
            for g in gens][-4:] == [3, 6, 9, 12]
    g3, g6, g9, g12 = gens[-4:]

    # poison 1 (bit-rot): flip a byte in gen-6's committed shard
    shard = lineage / g6 / "shard_0.npz"
    raw = bytearray(shard.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    shard.write_bytes(bytes(raw))

    reg = MetricsRegistry()
    # poison 2 (latency): replicas serving gen-9 sleep inside inference
    pool = ServingPool(f"{_POOL_WORKERS}:stub_server", replicas=2,
                       min_replicas=1, max_replicas=4,
                       workdir=str(tmp_path / "pool"), registry=reg,
                       extra_env={"TDL_FAULT_SPEC":
                                  f"latency_inject@value=0.5,model={g9}"}
                       ).start()
    c = None
    try:
        assert pool.wait_ready(60.0)
        c = FleetController(
            str(ckroot), pool, workdir=str(tmp_path / "deploy"),
            eval_fn=eval_candidate, eval_thresholds={"score": 0.3},
            regression_band=0.15,
            trace=TraceSpec(duration_s=2.0, base_rate=30.0, seed=18),
            slo_threshold_ms=200.0, burn_window_s=0.5,
            retries=1, retry_backoff_s=0.1, registry=reg)
        c.run_once()

        cands = c.state["candidates"]
        # the healthy first generation promoted through the full chain...
        assert cands[g3]["status"] == "promoted"
        assert c.state["promoted"]["generation"] == g3
        # ...and each poison died at the EARLIEST gate that can catch it
        assert cands[g6]["rejected_by"]["gate"] == "integrity"
        assert cands[g9]["rejected_by"]["gate"] == "canary"
        assert cands[g9]["rejected_by"]["reason"].startswith("slo:")
        assert cands[g12]["rejected_by"]["gate"] == "eval"
        spiked = [v for v in cands[g12]["verdicts"] if v["gate"] == "eval"]
        healthy = [v for v in cands[g3]["verdicts"] if v["gate"] == "eval"]
        assert spiked[0]["evidence"]["metrics"]["score"] \
            < healthy[0]["evidence"]["metrics"]["score"] - 0.15

        # audit mirrors every rejection with gate + reason + evidence
        audit = json.load(open(c.audit_path))
        by_gen = {x["generation"]: x for x in audit["candidates"]}
        for g, gate in ((g6, "integrity"), (g9, "canary"), (g12, "eval")):
            bad = [v for v in by_gen[g]["verdicts"] if not v["ok"]]
            assert bad and bad[-1]["gate"] == gate
            assert bad[-1]["evidence"]
        rb = {e["generation"]: e for e in c._own_recorder.events()
              if e["kind"] == "deploy_rollback"} if c._own_recorder else {}
        # the controller self-records when unsupervised; either way the
        # rollback counters saw all three gates
        assert _counter_values(reg, "tdl_deploy_rollbacks_total") == {
            ("integrity",): 1, ("canary",): 1, ("eval",): 1}
        assert _counter_values(reg, "tdl_deploy_promotions_total") == {(): 1}

        # the promoted fleet serves the replayed traffic with only
        # 200/429 escaping the pool's front door
        rows = pool.describe()["replicas"]
        assert all(r["model"] and r["model"].endswith(g3) for r in rows)
        report = replay(TraceSpec(duration_s=2.0, base_rate=40.0, seed=7),
                        pool.port, n_clients=4,
                        payload=[[0.0, 0.0, 0.0, 0.0]])
        assert set(report["outcomes"]) <= {"200", "429"}
        assert report["outcomes"].get("200", 0) > 0
        assert audit["timeline"] and os.path.exists(audit["timeline"])
    finally:
        if c is not None:
            c.close()
        pool.stop()
