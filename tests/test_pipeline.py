"""Pipeline parallelism (GPipe) + tensor-parallel fit wiring.

SURVEY §2.10 PP/TP rows: loss-equality of the stage-sharded shard_map
pipeline vs plain single-device execution, and TP-vs-replicated numerical
equality through the standard MultiLayerNetwork fit path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    make_train_step,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel.pipeline import (
    make_pp_train_step,
    microbatch,
    pipeline_partition_specs,
    pipeline_transformer_params,
    spmd_pipeline,
    transformer_pp_loss_fn,
    unmicrobatch,
)


def _cfg(n_layers=4):
    return TransformerConfig(
        vocab_size=64, max_len=32, d_model=16, n_heads=2, n_layers=n_layers,
        d_ff=32, dropout=0.0, param_dtype=jnp.float32, compute_dtype=jnp.float32,
    )


def _batch(cfg, B=8, T=16, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "tokens": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
        "labels": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
        "weights": jnp.ones((B, T), jnp.float32),
    }


def _pp_mesh(dp=2, pipe=4):
    devs = np.array(jax.devices()[: dp * pipe]).reshape(dp, pipe)
    return Mesh(devs, ("dp", "pipe"))


class TestSpmdPipeline:
    def test_generic_pipeline_matches_sequential(self):
        """4-stage elementwise affine stages == sequential composition."""
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
        S, M, mb, D = 4, 6, 2, 8
        rs = np.random.RandomState(1)
        stacked = {
            "w": jnp.asarray(rs.randn(S, D).astype(np.float32)),
            "b": jnp.asarray(rs.randn(S, D).astype(np.float32)),
        }
        xs = jnp.asarray(rs.randn(M, mb, D).astype(np.float32))

        def stage(p, x):
            return jnp.tanh(x * p["w"] + p["b"])

        got = spmd_pipeline(stage, stacked, xs, mesh, data_axis=None)
        want = xs
        for s in range(S):
            want = jax.vmap(lambda x: stage({"w": stacked["w"][s], "b": stacked["b"][s]}, x))(want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)

    def test_pipeline_grads_match_sequential(self):
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
        S, M, mb, D = 4, 4, 2, 8
        rs = np.random.RandomState(2)
        stacked = {"w": jnp.asarray(rs.randn(S, D).astype(np.float32))}
        xs = jnp.asarray(rs.randn(M, mb, D).astype(np.float32))

        def stage(p, x):
            return jnp.tanh(x * p["w"])

        def pp_loss(params):
            return jnp.sum(spmd_pipeline(stage, params, xs, mesh, data_axis=None) ** 2)

        def seq_loss(params):
            h = xs
            for s in range(S):
                h = jnp.tanh(h * params["w"][s])
            return jnp.sum(h ** 2)

        g_pp = jax.grad(pp_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stacked)
        np.testing.assert_allclose(np.asarray(g_pp["w"]), np.asarray(g_seq["w"]),
                                   rtol=1e-5, atol=1e-5)

    def test_transformer_pp_loss_matches_single_device(self):
        cfg = _cfg(n_layers=4)
        params = init_params(jax.random.key(0), cfg)
        batch = _batch(cfg)
        want = float(loss_fn(params, batch, cfg, rng=None, train=False))

        mesh = _pp_mesh(dp=2, pipe=4)
        pp_params = pipeline_transformer_params(params, n_stages=4)
        specs = pipeline_partition_specs(pp_params)
        pp_params = jax.device_put(
            pp_params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                    is_leaf=lambda x: isinstance(x, P)))
        ppl = transformer_pp_loss_fn(cfg, n_microbatches=4, mesh=mesh)
        got = float(jax.jit(ppl)(pp_params, batch))
        assert abs(got - want) < 1e-5, (got, want)

    def test_transformer_pp_train_step_matches_single_device(self):
        cfg = _cfg(n_layers=4)
        params = init_params(jax.random.key(0), cfg)
        batch = _batch(cfg)

        # single-device baseline, dropout off / train=False parity path
        upd = Sgd(0.1)
        base_params = jax.tree.map(jnp.copy, params)

        def base_loss(p, b):
            return loss_fn(p, b, cfg, rng=None, train=False)

        @jax.jit
        def base_step(p, b):
            l, g = jax.value_and_grad(base_loss)(p, b)
            u, _ = upd.apply(g, {}, p, 0, 0)
            return jax.tree.map(lambda x, y: x - y, p, u), l

        mesh = _pp_mesh(dp=2, pipe=4)
        pp_params = pipeline_transformer_params(params, n_stages=4)
        specs = pipeline_partition_specs(pp_params)
        pp_params = jax.device_put(
            pp_params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                    is_leaf=lambda x: isinstance(x, P)))
        opt_state = upd.init(pp_params)
        pp_step = jax.jit(make_pp_train_step(cfg, upd, n_microbatches=4, mesh=mesh))

        losses_base, losses_pp = [], []
        for i in range(3):
            b = _batch(cfg, seed=i)
            base_params, l0 = base_step(base_params, b)
            pp_params, opt_state, l1 = pp_step(pp_params, opt_state, b, jnp.asarray(i))
            losses_base.append(float(l0))
            losses_pp.append(float(l1))
        np.testing.assert_allclose(losses_pp, losses_base, rtol=1e-4, atol=1e-5)
        # stacked blocks shard over pipe: each stage holds only its layers
        leaf = jax.tree.leaves(pp_params["blocks"])[0]
        assert "pipe" in leaf.sharding.spec

    def test_transformer_pp_respects_pad_mask_and_segments(self):
        """pad_mask/segments flow through the pipeline as aux inputs and
        match the single-device loss exactly."""
        cfg = _cfg(n_layers=4)
        params = init_params(jax.random.key(3), cfg)
        batch = _batch(cfg)
        rs = np.random.RandomState(9)
        batch["pad_mask"] = jnp.asarray(
            (rs.rand(8, 16) > 0.25).astype(np.float32))
        batch["segments"] = jnp.asarray(rs.randint(0, 2, (8, 16)), jnp.int32)
        want = float(loss_fn(params, batch, cfg, rng=None, train=False))

        mesh = _pp_mesh(dp=2, pipe=4)
        pp_params = pipeline_transformer_params(params, n_stages=4)
        specs = pipeline_partition_specs(pp_params)
        pp_params = jax.device_put(
            pp_params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                    is_leaf=lambda x: isinstance(x, P)))
        ppl = transformer_pp_loss_fn(cfg, n_microbatches=4, mesh=mesh)
        got = float(jax.jit(ppl)(pp_params, batch))
        assert abs(got - want) < 1e-5, (got, want)

    def test_data_axis_mismatch_raises(self):
        from deeplearning4j_tpu.parallel.pipeline import resolve_data_axis

        mesh = _pp_mesh(dp=2, pipe=4)
        assert resolve_data_axis(mesh, "auto") == "dp"
        with pytest.raises(ValueError):
            resolve_data_axis(mesh, "data")

    def test_microbatch_roundtrip(self):
        x = jnp.arange(24.0).reshape(8, 3)
        assert np.array_equal(np.asarray(unmicrobatch(microbatch(x, 4))), np.asarray(x))
        with pytest.raises(ValueError):
            microbatch(x, 3)


class TestTensorParallelFit:
    def test_tp_fit_matches_replicated(self):
        """MLN fit through ParallelTrainer with Megatron alternating rules ==
        plain single-device fit (GSPMD collectives are numerically exact)."""
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            InputType,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.sharding import alternating_dense_rules
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        def build():
            return (
                NeuralNetConfiguration.Builder()
                .seed(7)
                .updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_in=16, n_out=32, activation="relu"))
                .layer(DenseLayer(n_in=32, n_out=32, activation="relu"))
                .layer(OutputLayer(n_in=32, n_out=4))
                .set_input_type(InputType.feed_forward(16))
                .build()
            )

        rs = np.random.RandomState(3)
        batches = []
        for i in range(4):
            x = rs.randn(8, 16).astype(np.float32)
            y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 8)]
            batches.append(DataSet(x, y))

        base = MultiLayerNetwork(build()).init()
        for ds in batches:
            base._fit_batch(ds)

        tp = MultiLayerNetwork(build()).init()
        mesh = build_mesh(data=2, model=4)
        trainer = ParallelTrainer(tp, mesh, sharding_rules=alternating_dense_rules())
        trainer.fit(ListDataSetIterator(batches, batch_size=8))

        # TP params actually sharded on the model axis
        w0 = tp.params_["0"]["W"]
        assert "model" in str(w0.sharding.spec)
        for k in base.params_:
            for name in base.params_[k]:
                np.testing.assert_allclose(
                    np.asarray(tp.params_[k][name]), np.asarray(base.params_[k][name]),
                    rtol=2e-5, atol=2e-5,
                    err_msg=f"param {k}/{name} diverged under TP")
