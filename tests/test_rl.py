"""RL (DQN) tests on a deterministic toy MDP (SURVEY §2.7 R1)."""

import numpy as np
import pytest

from deeplearning4j_tpu.rl import ExpReplay, QLearningConfiguration, QLearningDiscrete
from deeplearning4j_tpu.rl.mdp import SimpleToyMDP


def test_exp_replay_ring_buffer():
    rep = ExpReplay(max_size=4, batch_size=2, seed=0)
    for i in range(6):
        rep.store(np.array([i]), i % 2, float(i), np.array([i + 1]), False)
    assert len(rep) == 4  # ring evicted oldest
    s, a, r, s2, d = rep.sample()
    assert s.shape == (2, 1) and r.min() >= 2.0  # entries 0,1 evicted


def test_dqn_learns_chain_mdp():
    mdp = SimpleToyMDP(n=5, max_steps=30)
    cfg = QLearningConfiguration(
        seed=3, max_step=2500, batch_size=32, update_start=64,
        target_dqn_update_freq=100, eps_anneal_steps=1200, min_epsilon=0.05,
        gamma=0.95, max_epoch_step=30)
    learner = QLearningDiscrete(mdp, cfg, hidden=32)
    learner.train()
    policy = learner.get_policy()
    # greedy policy must walk straight to the goal: 4 steps, reward ~ +10
    total = policy.play(SimpleToyMDP(n=5, max_steps=30))
    assert total > 9.0, total
    # epsilon annealed
    assert abs(learner.epsilon() - cfg.min_epsilon) < 1e-6
