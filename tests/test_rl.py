"""RL (DQN) tests on a deterministic toy MDP (SURVEY §2.7 R1)."""

import numpy as np
import pytest

from deeplearning4j_tpu.rl import ExpReplay, QLearningConfiguration, QLearningDiscrete
from deeplearning4j_tpu.rl.mdp import SimpleToyMDP


def test_exp_replay_ring_buffer():
    rep = ExpReplay(max_size=4, batch_size=2, seed=0)
    for i in range(6):
        rep.store(np.array([i]), i % 2, float(i), np.array([i + 1]), False)
    assert len(rep) == 4  # ring evicted oldest
    s, a, r, s2, d = rep.sample()
    assert s.shape == (2, 1) and r.min() >= 2.0  # entries 0,1 evicted


def test_dqn_learns_chain_mdp():
    mdp = SimpleToyMDP(n=5, max_steps=30)
    cfg = QLearningConfiguration(
        seed=3, max_step=2500, batch_size=32, update_start=64,
        target_dqn_update_freq=100, eps_anneal_steps=1200, min_epsilon=0.05,
        gamma=0.95, max_epoch_step=30)
    learner = QLearningDiscrete(mdp, cfg, hidden=32)
    learner.train()
    policy = learner.get_policy()
    # greedy policy must walk straight to the goal: 4 steps, reward ~ +10
    total = policy.play(SimpleToyMDP(n=5, max_steps=30))
    assert total > 9.0, total
    # epsilon annealed
    assert abs(learner.epsilon() - cfg.min_epsilon) < 1e-6


class TestHistoryProcessor:
    def test_stack_skip_scale(self):
        from deeplearning4j_tpu.rl import HistoryProcessor, HistoryProcessorConfiguration

        hp = HistoryProcessor(HistoryProcessorConfiguration(
            history_length=3, rescaled_width=8, rescaled_height=8,
            cropping_width=6, cropping_height=6, offset_x=1, offset_y=1,
            skip_frame=2))
        f0 = np.full((16, 16, 3), 255, np.uint8)
        hp.start(f0)
        h = hp.history()
        assert h.shape == (3, 6, 6)
        np.testing.assert_allclose(h, 1.0)          # scaled to [0,1]
        # skip_frame=2: frame 1 skipped, frame 2 recorded
        assert not hp.record(np.zeros((16, 16, 3), np.uint8))
        assert hp.record(np.zeros((16, 16, 3), np.uint8))
        h = hp.history()
        np.testing.assert_allclose(h[-1], 0.0)      # newest is the dark frame
        np.testing.assert_allclose(h[0], 1.0)       # oldest still bright

    def test_grayscale_luma(self):
        from deeplearning4j_tpu.rl import HistoryProcessor, HistoryProcessorConfiguration

        hp = HistoryProcessor(HistoryProcessorConfiguration(
            history_length=1, rescaled_width=4, rescaled_height=4,
            cropping_width=4, cropping_height=4, skip_frame=1))
        f = np.zeros((4, 4, 3), np.float32)
        f[..., 1] = 1.0  # pure green
        hp.start(f)
        np.testing.assert_allclose(hp.history()[0], 0.587, rtol=1e-5)


class TestAsyncNStep:
    def test_learns_toy_mdp(self):
        from deeplearning4j_tpu.rl import (
            AsyncNStepQLearningDiscrete,
            AsyncQLearningConfiguration,
        )
        from deeplearning4j_tpu.rl.mdp import SimpleToyMDP

        cfg = AsyncQLearningConfiguration(
            max_step=3000, n_step=5, num_threads=2, eps_anneal_steps=1500,
            target_dqn_update_freq=50, seed=5)
        ql = AsyncNStepQLearningDiscrete(lambda tid: SimpleToyMDP(n=5), cfg,
                                        hidden=32)
        ql.train()
        # workers must SURVIVE to max_step (a crashed worker leaves
        # global_steps short — the donation bug regression guard)
        assert ql.global_steps >= cfg.max_step, ql.global_steps
        assert len(ql.epoch_rewards) > 5
        # greedy policy must solve the chain (always-right = ~+10)
        score = ql.get_policy().play(SimpleToyMDP(n=5))
        assert score > 9.0, score
