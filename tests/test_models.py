"""Zoo + flagship transformer tests (SURVEY §2.4 C15, §3.3)."""

import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.common import jax_compat
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.models import (
    LeNet,
    ResNet50,
    TextGenerationLSTM,
    TransformerConfig,
    transformer_init,
    transformer_loss,
    transformer_partition_specs,
)
from deeplearning4j_tpu.models.transformer import forward, make_train_step
from deeplearning4j_tpu.nn.updaters import Adam


def test_lenet_trains():
    net = LeNet().init()
    rs = np.random.RandomState(0)
    x = rs.randn(8, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 8)]
    s0 = None
    for _ in range(3):
        net.fit(DataSet(x, y))
        s0 = s0 or net.score_
    assert net.score_ < s0  # loss decreases on the fixed batch
    assert net.num_params() == 1256080


def test_resnet50_builds_and_steps():
    net = ResNet50(num_classes=10, input_shape=(3, 32, 32)).init()
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 32, 32).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 2)]
    net.fit(DataSet(x, y))
    assert np.isfinite(net.score_)


def test_resnet50_imagenet_param_count():
    conf = ResNet50(num_classes=1000).conf()
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    net = ComputationGraph(conf)
    net.init()
    n = sum(int(np.prod(w.shape)) for lp in net.params_.values() for w in lp.values())
    # Keras/dl4j-zoo ResNet50 reports 25,636,712 at 1000 classes, which counts
    # conv biases (26,560) and BN moving mean/var (53,120). This build uses
    # bias-free convs into BN (standard) and keeps BN stats as non-param state:
    # 25,636,712 - 26,560 - 53,120 = 25,557,032 trainable parameters.
    assert n == 25_557_032


def test_char_lstm_tbptt_trains():
    net = TextGenerationLSTM(vocab_size=12, hidden=16, layers=1, tbptt_length=8).init()
    rs = np.random.RandomState(0)
    x = np.eye(12, dtype=np.float32)[rs.randint(0, 12, (2, 20))].transpose(0, 2, 1)
    y = np.eye(12, dtype=np.float32)[rs.randint(0, 12, (2, 20))].transpose(0, 2, 1)
    net.fit(DataSet(x, y))
    assert np.isfinite(net.score_)


def test_transformer_dp_tp_train_step():
    cfg = TransformerConfig.tiny()
    params = transformer_init(jax.random.key(0), cfg)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
    specs = transformer_partition_specs(cfg)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(params, pshard)
    upd = Adam(1e-3)
    opt = upd.init(params)
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (8, 128)), jnp.int32)
    batch = {"tokens": toks, "labels": toks,
             "weights": jnp.ones((8, 128), jnp.float32)}
    batch = {k: jax.device_put(v, NamedSharding(mesh, P("dp", None)))
             for k, v in batch.items()}
    step = jax.jit(make_train_step(cfg, upd), donate_argnums=(0, 1))
    with jax_compat.set_mesh(mesh):
        params, opt, loss = step(params, opt, batch, jnp.asarray(0, jnp.int32),
                                 jax.random.key(1))
    assert np.isfinite(float(loss))


def test_transformer_ring_loss_matches_xla():
    """Sequence-parallel ring attention path computes the same loss."""
    cfg_x = TransformerConfig.tiny(dropout=0.0)
    cfg_r = TransformerConfig.tiny(dropout=0.0, attn_impl="ring", sequence_axis="sp")
    params = transformer_init(jax.random.key(0), cfg_x)
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(0, cfg_x.vocab_size, (4, 128)), jnp.int32)
    batch = {"tokens": toks, "labels": toks, "weights": jnp.ones((4, 128), jnp.float32)}
    l_ref = float(transformer_loss(params, batch, cfg_x, None, False))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2), ("dp", "tp", "sp"))
    specs = transformer_partition_specs(cfg_r)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                          is_leaf=lambda x: isinstance(x, P))
    params_s = jax.device_put(params, pshard)
    batch_s = {k: jax.device_put(v, NamedSharding(mesh, P("dp", "sp")))
               for k, v in batch.items()}
    with jax_compat.set_mesh(mesh):
        l_ring = float(jax.jit(lambda p, b: transformer_loss(p, b, cfg_r, None, False))(
            params_s, batch_s))
    assert abs(l_ref - l_ring) < 1e-3


def test_graft_entry():
    root = pathlib.Path(__file__).resolve().parents[1]
    spec = importlib.util.spec_from_file_location("graft_entry", root / "__graft_entry__.py")
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    m.dryrun_multichip(8)


class TestSquadFineTune:
    """BASELINE configs[4] shape: BERT span-prediction fine-tune."""

    def test_qa_head_learns_spans(self):
        import jax

        from deeplearning4j_tpu.models.transformer import (
            TransformerConfig, init_params, init_qa_head,
            make_qa_train_step, qa_forward,
        )
        from deeplearning4j_tpu.nn.updaters import Adam

        cfg = TransformerConfig.tiny(dropout=0.0)
        params = init_params(jax.random.key(0), cfg)
        qa = init_qa_head(jax.random.key(1), cfg)
        updater = Adam(5e-3)
        opt, qopt = updater.init(params), updater.init(qa)
        step = jax.jit(make_qa_train_step(cfg, updater),
                       donate_argnums=(0, 1, 2, 3))

        rs = np.random.RandomState(0)
        B, T = 8, 24
        toks = rs.randint(3, cfg.vocab_size, (B, T)).astype(np.int32)
        # answer span marked by sentinel tokens 1 (start) and 2 (end)
        starts = rs.randint(1, T - 4, B).astype(np.int32)
        ends = (starts + rs.randint(1, 3, B)).astype(np.int32)
        for b in range(B):
            toks[b, starts[b]] = 1
            toks[b, ends[b]] = 2
        segs = np.zeros((B, T), np.int32)
        batch = {"tokens": jnp.asarray(toks), "segments": jnp.asarray(segs),
                 "start_positions": jnp.asarray(starts),
                 "end_positions": jnp.asarray(ends)}
        rng = jax.random.key(2)
        first = None
        for i in range(120):
            params, qa, opt, qopt, loss = step(params, qa, opt, qopt, batch,
                                               jnp.asarray(i, jnp.int32), rng)
            if i == 0:
                first = float(loss)
        last = float(loss)
        assert last < first * 0.2, (first, last)
        s_log, e_log = qa_forward(params, qa, batch["tokens"], cfg,
                                  segments=batch["segments"])
        acc = float(np.mean(np.argmax(np.asarray(s_log), -1) == starts))
        assert acc > 0.7, acc
