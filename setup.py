from setuptools import find_packages, setup

setup(
    name="deeplearning4j-tpu",
    version="0.1.0",
    description="TPU-native deep-learning framework with the capability surface of Deeplearning4j",
    packages=find_packages(include=["deeplearning4j_tpu", "deeplearning4j_tpu.*"]),
    python_requires=">=3.10",
    # jax/flax/optax/numpy are provided by the environment; no pinned deps here
)
